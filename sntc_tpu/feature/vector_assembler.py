"""VectorAssembler — concatenate numeric columns into one feature vector.

Behavioral spec: SURVEY.md §2.2 (upstream ``ml/feature/VectorAssembler.scala``
[U]): dense concatenation in declared column order; ``handleInvalid`` is
``error`` (raise on NaN), ``skip`` (drop rows), or ``keep`` (pass NaN
through).  Output is a ``(N, D)`` float32 vector column — this framework's
``VectorUDT`` analog (sntc_tpu.core.frame).
"""

from __future__ import annotations

from typing import List

import numpy as np

from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


class VectorAssembler(Transformer):
    inputCols = Param("input column names, concatenated in order")
    outputCol = Param("output vector column", default="features")
    handleInvalid = Param(
        "how to handle NaN/Inf rows: error | skip | keep",
        default="error",
        validator=validators.one_of("error", "skip", "keep"),
    )

    def transform(self, frame: Frame) -> Frame:
        names: List[str] = self.getInputCols()
        cols = [frame[name] for name in names]
        widths = [1 if c.ndim == 1 else c.shape[1] for c in cols]
        # single allocation, cast-on-assign — no per-column intermediate
        # copies (this runs per micro-batch on the serving hot path [B:11])
        X = np.empty((frame.num_rows, sum(widths)), np.float32)
        off = 0
        for col, w in zip(cols, widths):
            if col.ndim == 1:
                X[:, off] = col
            else:
                X[:, off : off + w] = col
            off += w

        mode = self.getHandleInvalid()
        if mode != "keep":
            invalid = ~np.isfinite(X).all(axis=1)
            if invalid.any():
                if mode == "error":
                    raise ValueError(
                        f"VectorAssembler: {int(invalid.sum())} rows contain "
                        "NaN/Inf (handleInvalid='error'); clean the data or "
                        "use handleInvalid='skip'"
                    )
                frame = frame.filter(~invalid)
                X = X[~invalid]
        return frame.with_column(self.getOutputCol(), X)
