"""VectorAssembler — concatenate numeric columns into one feature vector.

Behavioral spec: SURVEY.md §2.2 (upstream ``ml/feature/VectorAssembler.scala``
[U]): dense concatenation in declared column order; ``handleInvalid`` is
``error`` (raise on NaN), ``skip`` (drop rows), or ``keep`` (pass NaN
through).  Output is a ``(N, D)`` float32 vector column — this framework's
``VectorUDT`` analog (sntc_tpu.core.frame).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List

import numpy as np

from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators

# assembly memo, keyed on the IDENTITY of the input column arrays (Frames
# are immutable and share column arrays across with_column/rename, so the
# same columns ⇒ the same stack).  Re-fitting on one dataset then reuses
# one X object, which keeps the downstream device-residency cache
# (sntc_tpu.parallel.collectives) hot — without this, every fit restacks
# 62 MB AND re-uploads it.  Input columns are held by WEAK reference: a
# dead column invalidates (and sweeps) the entry, so dropping the dataset
# frees the memo too, and a recycled id can never false-hit.  Shares the
# ``SNTC_DEVICE_CACHE_MB=0`` kill switch with the device cache.
_ASSEMBLE_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()
_ASSEMBLE_CACHE_MAX = 4
# memoize only fit-scale stacks: serving micro-batches (a fresh small
# frame per batch) would churn insert+sweep on the [B:11] hot path for
# entries that can never hit again
_ASSEMBLE_MEMO_MIN_BYTES = 8 << 20


class VectorAssembler(Transformer):
    inputCols = Param("input column names, concatenated in order")
    outputCol = Param("output vector column", default="features")
    handleInvalid = Param(
        "how to handle NaN/Inf rows: error | skip | keep",
        default="error",
        validator=validators.one_of("error", "skip", "keep"),
    )

    def transform(self, frame: Frame) -> Frame:
        import weakref

        from sntc_tpu.parallel.collectives import _device_cache_max_bytes

        names: List[str] = self.getInputCols()
        cols = [frame[name] for name in names]
        mode = self.getHandleInvalid()

        widths = [1 if c.ndim == 1 else c.shape[1] for c in cols]
        memo_on = (
            _device_cache_max_bytes() > 0
            and frame.num_rows * sum(widths) * 4 >= _ASSEMBLE_MEMO_MIN_BYTES
        )
        if _ASSEMBLE_CACHE:
            # sweep entries whose input columns were garbage-collected
            for k in [
                k for k, e in _ASSEMBLE_CACHE.items()
                if any(r() is None for r in e[0])
            ]:
                del _ASSEMBLE_CACHE[k]
        key = (tuple(id(c) for c in cols), mode)
        hit = _ASSEMBLE_CACHE.get(key) if memo_on else None
        if hit is not None and all(
            r() is c for r, c in zip(hit[0], cols)
        ):
            _ASSEMBLE_CACHE.move_to_end(key)
            X, invalid = hit[1], hit[2]
        else:
            if cols and all(c.ndim == 1 for c in cols):
                # all-1-D-columns fast path: ONE C-level stack+cast (4×
                # the per-column assign loop — this runs per micro-batch
                # on the serving hot path [B:11]); the transposed view
                # multiplies/converts downstream at full speed, so no
                # contiguity copy.  (N, 1) 2-D columns must take the
                # assign loop: np.array would stack them to 3-D
                X = np.array(cols, dtype=np.float32).T
            else:
                # single allocation, cast-on-assign — no per-column
                # intermediate copies
                X = np.empty((frame.num_rows, sum(widths)), np.float32)
                off = 0
                for col, w in zip(cols, widths):
                    if col.ndim == 1:
                        X[:, off] = col
                    else:
                        X[:, off : off + w] = col
                    off += w

            invalid = None
            if mode != "keep":
                bad = ~np.isfinite(X).all(axis=1)
                if bad.any():
                    if mode == "error":
                        raise ValueError(
                            f"VectorAssembler: {int(bad.sum())} rows contain "
                            "NaN/Inf (handleInvalid='error'); clean the data "
                            "or use handleInvalid='skip'"
                        )
                    invalid = bad
            if memo_on:
                try:
                    refs = tuple(weakref.ref(c) for c in cols)
                except TypeError:
                    refs = None  # non-weakref-able column type
                if refs is not None:
                    _ASSEMBLE_CACHE[key] = (refs, X, invalid)
                    while len(_ASSEMBLE_CACHE) > _ASSEMBLE_CACHE_MAX or (
                        len(_ASSEMBLE_CACHE) > 1
                        and sum(
                            e[1].nbytes for e in _ASSEMBLE_CACHE.values()
                        )
                        > (2 << 30)
                    ):
                        _ASSEMBLE_CACHE.popitem(last=False)

        if invalid is not None:  # skip mode with rows to drop
            frame = frame.filter(~invalid)
            X = X[~invalid]
        return frame.with_column(self.getOutputCol(), X)
