"""Word2Vec — skip-gram word embeddings.

Behavioral spec: upstream ``ml/feature/Word2Vec.scala`` →
``mllib/feature/Word2Vec.scala`` [U]: token-array input, ``vectorSize``
(100), ``windowSize`` (5), ``minCount`` (5) vocabulary floor,
``stepSize`` (0.025) with linear decay, ``maxIter`` epochs, ``seed``;
model surface: ``getVectors`` (word → vector frame), ``findSynonyms``
(cosine nearest words), ``transform`` = the AVERAGE of a document's
word vectors (Spark's document embedding).

Documented delta: Spark trains skip-gram with HIERARCHICAL SOFTMAX — a
Huffman-tree walk per token whose pointer-chasing defeats a systolic
array; here the same skip-gram objective trains with NEGATIVE SAMPLING
(Mikolov et al.'s other standard estimator): every step is dense
gathers + batched dot products + scatter-add gradients, and the WHOLE
training epoch runs as ONE jitted ``lax.scan`` over minibatches (the
unigram^0.75 negative table is sampled inside the step from the carried
PRNG key).  The two estimators learn embeddings of the same quality
class; word-for-word numeric parity with Spark is not defined for
either (both are seed-chaotic SGD).

TPU design: carry = (W_in [V,E], W_out [V,E], key); per step a [B]
center gather, [B] context gather, [B,NEG] negative gathers →
``log σ(u·v)`` losses; autodiff turns the gathers into scatter-adds.
"""

from __future__ import annotations

from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame, object_column
from sntc_tpu.core.params import Param, validators

_NEG = 5  # negatives per positive (Mikolov's small-corpus default)


@partial(jax.jit, static_argnames=("batch", "n_steps"))
def _train_epochs(pairs, probs_cum, w_in0, w_out0, key, lr0, *, batch,
                  n_steps):
    """All steps of all epochs as one ``lax.scan``.  ``pairs [P, 2]``
    (center, context) are pre-shuffled on host; step ``t`` trains on the
    rolling slice ``[t·B, (t+1)·B)`` mod P with linearly decayed lr."""
    p = pairs.shape[0]

    def step(carry, t):
        w_in, w_out, k = carry
        k, k_neg = jax.random.split(k)
        start = (t * batch) % p
        idx = (start + jnp.arange(batch)) % p
        centers = pairs[idx, 0]
        contexts = pairs[idx, 1]
        u = jax.random.uniform(k_neg, (batch, _NEG))
        negs = jnp.searchsorted(probs_cum, u)  # unigram^0.75 table

        def loss_fn(w_in, w_out):
            vc = w_in[centers]  # [B, E]
            uo = w_out[contexts]  # [B, E]
            un = w_out[negs]  # [B, NEG, E]
            pos = jax.nn.log_sigmoid((vc * uo).sum(-1))
            neg = jax.nn.log_sigmoid(
                -(vc[:, None, :] * un).sum(-1)
            ).sum(-1)
            return -(pos + neg).mean()

        g_in, g_out = jax.grad(loss_fn, argnums=(0, 1))(w_in, w_out)
        lr = lr0 * jnp.maximum(1.0 - t / n_steps, 1e-4)
        return (w_in - lr * g_in, w_out - lr * g_out, k), ()

    (w_in, w_out, _), _ = jax.lax.scan(
        step, (w_in0, w_out0, key), jnp.arange(n_steps)
    )
    return w_in, w_out


class _W2vParams:
    inputCol = Param("token-array column", default="tokens")
    outputCol = Param("output document-vector column", default="wordVectors")
    vectorSize = Param("embedding dimension", default=100,
                       validator=validators.gt(0))
    windowSize = Param("context window radius", default=5,
                       validator=validators.gt(0))
    minCount = Param("min corpus occurrences for the vocabulary", default=5,
                     validator=validators.gteq(0))
    maxIter = Param("training epochs", default=1, validator=validators.gt(0))
    stepSize = Param("initial learning rate (linear decay)", default=0.025,
                     validator=validators.gt(0))
    seed = Param("random seed", default=0)


class Word2Vec(_W2vParams, Estimator):
    def _fit(self, frame: Frame) -> "Word2VecModel":
        docs = [list(map(str, d)) for d in frame[self.getInputCol()]]
        counts: dict = {}
        for d in docs:
            for t in d:
                counts[t] = counts.get(t, 0) + 1
        vocab = sorted(
            (t for t, c in counts.items() if c >= int(self.getMinCount())),
            key=lambda t: (-counts[t], t),
        )
        if not vocab:
            raise ValueError(
                "empty vocabulary: no token reaches minCount="
                f"{self.getMinCount()}"
            )
        index = {t: i for i, t in enumerate(vocab)}
        v = len(vocab)
        e = int(self.getVectorSize())
        win = int(self.getWindowSize())

        pairs: List[tuple] = []
        for d in docs:
            ids = [index[t] for t in d if t in index]
            for i, c in enumerate(ids):
                for j in range(max(0, i - win), min(len(ids), i + win + 1)):
                    if j != i:
                        pairs.append((c, ids[j]))
        if not pairs:
            raise ValueError(
                "no skip-gram pairs: documents are too short for the "
                "window after minCount filtering"
            )
        rng = np.random.default_rng(self.getSeed())
        pairs_arr = np.asarray(pairs, np.int32)
        rng.shuffle(pairs_arr)

        freq = np.asarray([counts[t] for t in vocab], np.float64) ** 0.75
        probs_cum = np.cumsum(freq / freq.sum()).astype(np.float32)

        batch = int(min(1024, len(pairs_arr)))
        steps_per_epoch = max(1, len(pairs_arr) // batch)
        n_steps = steps_per_epoch * int(self.getMaxIter())
        w_in0 = (
            (rng.random((v, e), np.float32) - 0.5) / e
        ).astype(np.float32)
        w_out0 = np.zeros((v, e), np.float32)
        w_in, _ = _train_epochs(
            jnp.asarray(pairs_arr), jnp.asarray(probs_cum),
            jnp.asarray(w_in0), jnp.asarray(w_out0),
            jax.random.PRNGKey(int(self.getSeed())),
            jnp.float32(self.getStepSize()),
            batch=batch, n_steps=int(n_steps),
        )
        model = Word2VecModel(
            vocabulary=vocab, vectors=np.asarray(w_in, np.float32)
        )
        model.setParams(**self.paramValues())
        return model


class Word2VecModel(_W2vParams, Model):
    def __init__(self, vocabulary: List[str], vectors, **kwargs):
        super().__init__(**kwargs)
        self.vocabulary = list(vocabulary)
        self.vectors = np.asarray(vectors, np.float32)
        self._index = {t: i for i, t in enumerate(self.vocabulary)}

    def getVectors(self) -> Frame:
        return Frame({
            "word": object_column(self.vocabulary),
            "vector": self.vectors,
        })

    def findSynonyms(self, word: str, num: int) -> Frame:
        j = self._index.get(str(word))
        if j is None:
            raise KeyError(f"{word!r} is not in the vocabulary")
        q = self.vectors[j]
        w = self.vectors
        sim = (w @ q) / (
            np.linalg.norm(w, axis=1) * max(np.linalg.norm(q), 1e-12) + 1e-12
        )
        sim[j] = -np.inf  # Spark excludes the query word
        order = np.argsort(-sim)[:num]
        return Frame({
            "word": object_column([self.vocabulary[o] for o in order]),
            "similarity": sim[order].astype(np.float64),
        })

    def transform(self, frame: Frame) -> Frame:
        e = self.vectors.shape[1]
        out = np.zeros((frame.num_rows, e), np.float32)
        for r, doc in enumerate(frame[self.getInputCol()]):
            ids = [self._index[str(t)] for t in doc if str(t) in self._index]
            if ids:
                out[r] = self.vectors[ids].mean(axis=0)
        return frame.with_column(self.getOutputCol(), out)

    def _save_extra(self):
        return {"vocabulary": self.vocabulary}, {"vectors": self.vectors}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(vocabulary=extra["vocabulary"], vectors=arrays["vectors"])
        m.setParams(**params)
        return m
