"""VarianceThresholdSelector — drop (near-)constant features.

Behavioral spec: upstream ``ml/feature/VarianceThresholdSelector.scala``
[U] (Spark 3.1): keep features whose SAMPLE variance is strictly
greater than ``varianceThreshold`` (default 0.0 — drop constants).

TPU design: the variances come from the StandardScaler's one-pass SPMD
moments aggregate — no new reduction machinery; the transform is a
column gather.
"""

from __future__ import annotations

from typing import List

import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.parallel.collectives import shard_batch
from sntc_tpu.parallel.context import get_default_mesh


class _VtsParams:
    featuresCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="selectedFeatures")
    varianceThreshold = Param(
        "keep features with sample variance > this", default=0.0,
        validator=validators.gteq(0),
    )


class VarianceThresholdSelector(_VtsParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "VarianceThresholdSelectorModel":
        from sntc_tpu.feature.standard_scaler import standardization_moments

        mesh = self._mesh or get_default_mesh()
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError("featuresCol must be a vector column")
        X = X.astype(np.float32, copy=False)
        n = X.shape[0]
        xs, ws = shard_batch(mesh, X)
        n_w, _, var = standardization_moments(
            mesh, xs, ws, np.asarray(X[0]) if n else np.zeros(X.shape[1])
        )
        # standardization_moments returns the population form; Spark
        # compares the UNBIASED sample variance
        var = np.asarray(var, np.float64) * (n / max(n - 1, 1))
        selected = [
            int(j) for j in range(X.shape[1])
            if var[j] > float(self.getVarianceThreshold())
        ]
        model = VarianceThresholdSelectorModel(selectedFeatures=selected)
        model.setParams(**self.paramValues())
        return model


class VarianceThresholdSelectorModel(_VtsParams, Model):
    def __init__(self, selectedFeatures: List[int] = (), **kwargs):
        super().__init__(**kwargs)
        self.selectedFeatures = [int(j) for j in selectedFeatures]

    def _save_extra(self):
        return {"selectedFeatures": self.selectedFeatures}, {}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(selectedFeatures=extra["selectedFeatures"])
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()]
        return frame.with_column(
            self.getOutputCol(), np.asarray(X)[:, self.selectedFeatures]
        )
