"""OneHotEncoder / VectorSlicer / ElementwiseProduct.

Behavioral spec: upstream ``ml/feature/{OneHotEncoder,VectorSlicer,
ElementwiseProduct}.scala`` [U]:

  * OneHotEncoder: fit learns each input column's category count (max
    index + 1); transform maps index ``i`` to a one-hot vector.
    ``dropLast`` (default True) drops the final category (the all-zeros
    encoding, Spark's reference-level convention); ``handleInvalid``
    error (default) / keep (extra all-"invalid" category appended).
    Multi-column; output vectors are concatenated per column.
  * VectorSlicer: stateless gather of ``indices`` from a vector column.
  * ElementwiseProduct: stateless Hadamard product with ``scalingVec``.

TPU note: one-hot output feeds the estimators as a dense ``[N, D]``
block (XLA consumes dense one-hots natively — the MXU matmul against a
one-hot IS the gather); host-side the encoding is a single fancy-index
assignment per column.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from sntc_tpu.core.base import Estimator, Model, Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


class _OheParams:
    inputCols = Param("input index columns", default=None)
    outputCols = Param("output vector columns (same length)", default=None)
    dropLast = Param(
        "drop the last category (all-zeros encoding)", default=True,
        validator=validators.is_bool(),
    )
    handleInvalid = Param(
        "unseen-index handling: error | keep (extra category)",
        default="error",
        validator=validators.one_of("error", "keep"),
    )

    def _cols(self):
        ins = self.getInputCols()
        outs = self.getOutputCols()
        if not ins:
            raise ValueError("inputCols is required")
        outs = outs or [c + "_ohe" for c in ins]
        if len(ins) != len(outs):
            raise ValueError("inputCols and outputCols lengths differ")
        return ins, outs


class OneHotEncoder(_OheParams, Estimator):
    def _fit(self, frame: Frame) -> "OneHotEncoderModel":
        ins, _ = self._cols()
        sizes = []
        for c in ins:
            v = np.asarray(frame[c], np.float64)
            if len(v) and ((v < 0) | (v != np.floor(v))).any():
                raise ValueError(
                    f"OneHotEncoder: column {c!r} must hold non-negative "
                    "integer indices"
                )
            sizes.append(int(v.max()) + 1 if len(v) else 0)
        model = OneHotEncoderModel(categorySizes=sizes)
        model.setParams(**self.paramValues())
        return model


class OneHotEncoderModel(_OheParams, Model):
    def __init__(self, categorySizes: Sequence[int] = (), **kwargs):
        super().__init__(**kwargs)
        self.categorySizes = [int(s) for s in categorySizes]

    def _save_extra(self):
        return {"categorySizes": self.categorySizes}, {}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(categorySizes=extra["categorySizes"])
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        ins, outs = self._cols()
        drop = self.getDropLast()
        keep_invalid = self.getHandleInvalid() == "keep"
        out = frame
        for c, o, size in zip(ins, outs, self.categorySizes):
            idx = np.asarray(frame[c], np.int64)
            n = len(idx)
            invalid = (idx < 0) | (idx >= size)
            if invalid.any() and not keep_invalid:
                raise ValueError(
                    f"OneHotEncoder: column {c!r} has indices outside "
                    f"[0, {size}) (set handleInvalid='keep')"
                )
            # width: size (+1 invalid slot when keeping) (−1 when dropLast)
            width = size + (1 if keep_invalid else 0) - (1 if drop else 0)
            enc = np.zeros((n, max(width, 0)), np.float32)
            slot = np.where(invalid, size if keep_invalid else 0, idx)
            ok = slot < width  # dropLast: the last category stays all-zero
            rows = np.flatnonzero(ok)
            enc[rows, slot[rows]] = 1.0
            out = out.with_column(o, enc)
        return out


class VectorSlicer(Transformer):
    """Column gather from a vector column — stateless."""

    inputCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="sliced")
    indices = Param("indices to keep, in output order", default=None)

    def transform(self, frame: Frame) -> Frame:
        idx = self.getIndices()
        if not idx:
            raise ValueError("indices is required")
        X = frame[self.getInputCol()]
        idx = np.asarray(idx, np.int64)
        if (idx < 0).any() or (idx >= X.shape[1]).any():
            raise ValueError(
                f"indices out of range for vector width {X.shape[1]}"
            )
        return frame.with_column(
            self.getOutputCol(), np.ascontiguousarray(X[:, idx])
        )


class ElementwiseProduct(Transformer):
    """Hadamard product with a fixed scaling vector — stateless."""

    inputCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="scaled")
    scalingVec = Param("the per-dimension multiplier vector", default=None)

    def transform(self, frame: Frame) -> Frame:
        w = self.getScalingVec()
        if w is None:
            raise ValueError("scalingVec is required")
        X = frame[self.getInputCol()]
        w = np.asarray(w, np.float32)
        if w.shape != (X.shape[1],):
            raise ValueError(
                f"scalingVec length {w.shape[0]} != vector width {X.shape[1]}"
            )
        return frame.with_column(
            self.getOutputCol(), (X * w[None, :]).astype(np.float32)
        )
