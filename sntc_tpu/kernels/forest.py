"""Pallas TPU kernel: fused RF/GBT/DT ensemble traversal (r21).

The serving node-walk (``grower.forest_leaf_stats``) is ``max_depth``
rounds of data-dependent gathers — feature id at the current node, the
row's value of that feature, the node's threshold — which XLA lowers to
serialized dynamic-slice chains per level.  This kernel keeps one
(tree, row-block) tile resident in VMEM and replaces every gather with
an exact iota-mask select (one nonzero term per row, so float sums are
bit-exact) plus a final one-hot MXU matmul for the leaf-stat gather:

    for each (tree t, row-block r):
        node = 0
        repeat max_depth:
            f, thr   = select(node == iota_M, feature/threshold row)
            xv       = select(f == iota_F, X block)
            node     = 2*node + 1 + (xv >= thr)   where internal
        out[t, r] = onehot(node) @ leaf_stats[t]   # MXU, exact

Trees ride the grid, so the whole forest traverses in one launch with
no per-level host round-trips.  Exactness means the lowered-jnp twin
(``forest_leaf_stats`` itself) pins bitwise in f64 and f32 alike; the
documented tolerance keeps the f32 bound at ≤1e-5 rel for headroom
(docs/PERFORMANCE.md kernel-forge table).

Registered as ``forest_traversal`` in ``sntc_tpu.kernels.registry``;
``forest_fits_pallas`` guards the VMEM working set, interpret mode
backs the CPU tier-1 matrix, and a compile failure poisons exactly this
kernel's signature back onto the XLA node-walk.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from sntc_tpu.kernels.registry import KernelSpec, register_kernel

_ROW_BLOCK = 128  # rows per grid step (f32 lane tile)
_LANE = 128
_VMEM_BUDGET = 4 * 1024 * 1024  # in-kernel working set budget (bytes)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def forest_fits_pallas(
    n_nodes: int, n_features: int, n_stats: int, itemsize: int = 4
) -> bool:
    """True when one (tree, row-block) tile's working set — the node
    one-hot, the feature-select mask, and the padded leaf-stat block —
    fits the kernel's VMEM budget.  Beyond it (freak depth/width
    forests) callers stay on the XLA node-walk."""
    mp = _round_up(max(n_nodes, _LANE), _LANE)
    fp = _round_up(max(n_features, _LANE), _LANE)
    sp = _round_up(max(n_stats, _LANE), _LANE)
    work = _ROW_BLOCK * mp + _ROW_BLOCK * fp + mp * sp
    return work * itemsize <= _VMEM_BUDGET


def _forest_kernel(
    x_ref, feat_ref, thr_ref, leaf_ref, out_ref, *, max_depth, bn, mp, fp
):
    x = x_ref[...]  # [BN, Fp]
    feat = feat_ref[0, :]  # [Mp] int32 (-1 leaf, -2 absent)
    thr = thr_ref[0, :]  # [Mp]
    leaf = leaf_ref[0]  # [Mp, Sp]
    node = jnp.zeros((bn,), jnp.int32)
    cols_m = jax.lax.broadcasted_iota(jnp.int32, (bn, mp), 1)
    cols_f = jax.lax.broadcasted_iota(jnp.int32, (bn, fp), 1)
    zero_t = jnp.zeros((), thr.dtype)
    zero_x = jnp.zeros((), x.dtype)
    for _ in range(max_depth):
        at_node = cols_m == node[:, None]  # [BN, Mp] one column per row
        f = jnp.sum(jnp.where(at_node, feat[None, :], 0), axis=1)
        t = jnp.sum(jnp.where(at_node, thr[None, :], zero_t), axis=1)
        is_internal = f >= 0
        fc = jnp.where(is_internal, f, 0)
        xv = jnp.sum(jnp.where(cols_f == fc[:, None], x, zero_x), axis=1)
        go_right = (xv >= t).astype(jnp.int32)
        node = jnp.where(is_internal, 2 * node + 1 + go_right, node)
    onehot = (cols_m == node[:, None]).astype(leaf.dtype)
    out_ref[0] = jnp.dot(onehot, leaf, preferred_element_type=leaf.dtype)


@functools.partial(
    jax.jit, static_argnames=("max_depth", "interpret")
)
def forest_leaf_stats_pallas(
    X: jnp.ndarray,  # [N, F]
    feature: jnp.ndarray,  # [T, M] int32
    threshold: jnp.ndarray,  # [T, M]
    leaf_stats: jnp.ndarray,  # [T, M, S]
    *,
    max_depth: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Kernel twin of :func:`sntc_tpu.models.tree.grower.forest_leaf_stats`
    — leaf stats ``[T, N, S]`` for every (tree, row)."""
    n, f = X.shape
    t, m = feature.shape
    s = leaf_stats.shape[2]
    np_ = _round_up(max(n, _ROW_BLOCK), _ROW_BLOCK)
    fp = _round_up(max(f, _LANE), _LANE)
    mp = _round_up(max(m, _LANE), _LANE)
    sp = _round_up(max(s, _LANE), _LANE)
    if np_ != n or fp != f:
        X = jnp.pad(X, ((0, np_ - n), (0, fp - f)))
    if mp != m:
        # padded nodes are unreachable (the walk never leaves [0, M));
        # -2 marks them absent exactly like the grower's layout
        feature = jnp.pad(feature, ((0, 0), (0, mp - m)), constant_values=-2)
        threshold = jnp.pad(threshold, ((0, 0), (0, mp - m)))
        leaf_stats = jnp.pad(leaf_stats, ((0, 0), (0, mp - m), (0, 0)))
    if sp != s:
        leaf_stats = jnp.pad(leaf_stats, ((0, 0), (0, 0), (0, sp - s)))

    grid = (t, np_ // _ROW_BLOCK)
    out = pl.pallas_call(
        functools.partial(
            _forest_kernel,
            max_depth=max_depth, bn=_ROW_BLOCK, mp=mp, fp=fp,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_ROW_BLOCK, fp), lambda ti, r: (r, 0)),  # X
            pl.BlockSpec((1, mp), lambda ti, r: (ti, 0)),  # feature
            pl.BlockSpec((1, mp), lambda ti, r: (ti, 0)),  # threshold
            pl.BlockSpec((1, mp, sp), lambda ti, r: (ti, 0, 0)),  # leaf
        ],
        out_specs=pl.BlockSpec(
            (1, _ROW_BLOCK, sp), lambda ti, r: (ti, r, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((t, np_, sp), leaf_stats.dtype),
        interpret=interpret,
    )(X, feature, threshold, leaf_stats)
    return out[:, :n, :s]


def traverse_forest(
    X, feature, threshold, leaf_stats, *, max_depth: int,
    traversal: str = "xla",
):
    """Traversal dispatch inside the jitted serve programs: the
    ``traversal`` token is a static argument resolved by the registry
    ladder at the ``_predict_all_dev`` boundary (``"xla"`` is the
    lowered-jnp twin the kernel is pinned against)."""
    if traversal in ("pallas", "interpret"):
        return forest_leaf_stats_pallas(
            X, feature, threshold, leaf_stats,
            max_depth=max_depth, interpret=(traversal == "interpret"),
        )
    from sntc_tpu.models.tree.grower import forest_leaf_stats

    return forest_leaf_stats(
        X, feature, threshold, leaf_stats, max_depth=max_depth
    )


register_kernel(
    KernelSpec(
        name="forest_traversal",
        module="sntc_tpu/kernels/forest.py",
        guard_name="forest_fits_pallas",
        guard=forest_fits_pallas,
        tolerance="bitwise f64 / <=1e-5 rel f32",
        fallback="XLA node-walk (grower.forest_leaf_stats)",
    )
)
