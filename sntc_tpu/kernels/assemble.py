"""Pallas kernel: bucketed pad + mask + assemble for the serve path (r21).

``BatchPredictor`` rounds every batch up to a shape bucket before
dispatch (``serve/transform.py``): the frame's columns are padded to
the bucket by repeating the last row (``Frame.pad_rows``) and a
``VALID_COL`` mask marking the real rows is threaded through the
transform.  This module gives that step a kernel twin:
:func:`pad_assemble` pads each float column with a one-hot
gather-matmul — ``out[r] = a[min(r, N-1)]`` expressed as
``onehot(min(row, N-1)) @ a``, exact per element, so the result is
bitwise identical to the numpy repeat-last-row twin — and assembles the
bucketed frame with the validity mask attached.

Non-float columns (ints, bools, strings) and anything the
``pad_fits_pallas`` guard rejects take the numpy twin column-by-column;
a compile failure poisons exactly this kernel's (shape, dtype, bucket)
signature through the shared ladder and the batch is served on the
twin.  Registered as ``pad_assemble`` in ``sntc_tpu.kernels.registry``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from sntc_tpu.kernels.registry import (
    KernelSpec,
    register_kernel,
    serve_kernel_call,
)

_ROW_BLOCK = 128
_LANE = 128
_VMEM_BUDGET = 4 * 1024 * 1024


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def pad_fits_pallas(n_rows: int, n_cols: int, itemsize: int = 8) -> bool:
    """True when one output row-block's working set — the gather
    one-hot against the whole (padded) input plus the input and output
    blocks — fits the VMEM budget.  Serve buckets are small (the
    predictor's bucket ladder tops out well under a million rows ×
    a few hundred columns); anything wider pads on the host."""
    np_in = _round_up(max(n_rows, _LANE), _LANE)
    cp = _round_up(max(n_cols, _LANE), _LANE)
    work = _ROW_BLOCK * np_in + np_in * cp + _ROW_BLOCK * cp
    return work * itemsize <= _VMEM_BUDGET


def _pad_kernel(x_ref, o_ref, *, bb, n_in, np_in):
    r = pl.program_id(0)
    rows = r * bb + jax.lax.broadcasted_iota(jnp.int32, (bb, np_in), 0)
    src = jnp.minimum(rows, n_in - 1)  # repeat-last-row semantics
    cols = jax.lax.broadcasted_iota(jnp.int32, (bb, np_in), 1)
    onehot = (cols == src).astype(x_ref.dtype)
    o_ref[...] = jnp.dot(
        onehot, x_ref[...], preferred_element_type=x_ref.dtype
    )


@functools.partial(jax.jit, static_argnames=("target", "interpret"))
def pad_rows_pallas(
    a: jnp.ndarray, *, target: int, interpret: bool = False
) -> jnp.ndarray:
    """Pad one ``[N, C]`` column block to ``[target, C]`` by repeating
    the last row (the :meth:`Frame.pad_rows` contract, bit-exact)."""
    n, c = a.shape
    np_in = _round_up(max(n, _LANE), _LANE)
    cp = _round_up(max(c, _LANE), _LANE)
    tp = _round_up(max(target, _ROW_BLOCK), _ROW_BLOCK)
    if np_in != n or cp != c:
        a = jnp.pad(a, ((0, np_in - n), (0, cp - c)))
    out = pl.pallas_call(
        functools.partial(
            _pad_kernel, bb=_ROW_BLOCK, n_in=n, np_in=np_in
        ),
        grid=(tp // _ROW_BLOCK,),
        in_specs=[pl.BlockSpec((np_in, cp), lambda r: (0, 0))],
        out_specs=pl.BlockSpec((_ROW_BLOCK, cp), lambda r: (r, 0)),
        out_shape=jax.ShapeDtypeStruct((tp, cp), a.dtype),
        interpret=interpret,
    )(a)
    return out[:target, :c]


def _pad_column_np(a: np.ndarray, target: int) -> np.ndarray:
    """The numpy twin — exactly ``Frame.pad_rows`` on one column."""
    pad = target - a.shape[0]
    tail = np.broadcast_to(a[-1:], (pad,) + a.shape[1:])
    return np.concatenate([a, tail])


def pad_assemble(frame, target: int, valid: np.ndarray):
    """Bucket-pad ``frame`` to ``target`` rows and attach the
    ``VALID_COL`` mask — the kernel-tier twin of
    ``frame.pad_rows(target).with_column(VALID_COL, valid)``.

    Float columns route through :func:`pad_rows_pallas` behind the
    shared registry ladder (guard reject / kernels-off / poisoned →
    numpy twin, counted); everything else pads on the host."""
    from sntc_tpu.core.frame import Frame
    from sntc_tpu.serve.transform import VALID_COL

    import jax

    n = frame.num_rows
    cols = {}
    # f64 columns may only ride the kernel when jax carries f64
    # natively — without jax_enable_x64 the upload would downcast and
    # break the bitwise contract (same gate as fuse.registry's F64
    # read policy)
    f64_ok = bool(jax.config.jax_enable_x64)
    for name in frame.columns:
        a = frame[name]
        if (
            (
                a.dtype == np.float32
                or (a.dtype == np.float64 and f64_ok)
            )
            and a.ndim in (1, 2)
            and n > 0
        ):
            a2 = a if a.ndim == 2 else a[:, None]
            padded = serve_kernel_call(
                "pad_assemble",
                (a2,),
                lambda impl, a2=a2: np.asarray(
                    pad_rows_pallas(
                        jnp.asarray(a2), target=target,
                        interpret=(impl == "interpret"),
                    )
                ),
                lambda a=a: _pad_column_np(a, target),
                static=(target,),
                guard_kwargs={
                    "n_rows": n,
                    "n_cols": a2.shape[1],
                    "itemsize": a2.dtype.itemsize,
                },
            )
            if padded.ndim != a.ndim:  # kernel path returns [target, 1]
                padded = padded[:, 0]
            cols[name] = padded
        else:
            cols[name] = _pad_column_np(a, target)
    cols[VALID_COL] = np.asarray(valid, dtype=bool)
    return Frame._wrap(cols, int(target))


register_kernel(
    KernelSpec(
        name="pad_assemble",
        module="sntc_tpu/kernels/assemble.py",
        guard_name="pad_fits_pallas",
        guard=pad_fits_pallas,
        tolerance="bitwise (exact one-hot gather)",
        fallback="numpy Frame.pad_rows twin, column-by-column",
    )
)
