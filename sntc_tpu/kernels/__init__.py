"""Hand-written Pallas serving kernels + the kernel capability registry.

The serving hot path gets a kernel tier (r21): ``forest.py`` fuses the
RF/GBT/DT ensemble node-walk, ``assemble.py`` fuses the bucketed
pad+mask+assemble step, and the fit-side histogram kernel
(``sntc_tpu/ops/pallas_histogram.py``) registers through the same
table.  ``registry.py`` owns selection (``SNTC_SERVE_KERNELS``),
fit-guards, the ``kernel.compile`` poison/fallback ladder, and the
``sntc_kernel_*`` evidence; ``scripts/check_kernel_registry.py`` pins
registry ⇔ docs ⇔ tests in tier-1.
"""

from sntc_tpu.kernels.registry import (  # noqa: F401
    KernelSpec,
    kernel_dispatch,
    kernel_stats,
    registered_kernels,
    resolve_impl,
    resolve_serve_kernels,
    serve_kernel_call,
)
