"""The serving-kernel capability registry (r21).

Every hand-written Pallas kernel in this codebase is declared here as a
:class:`KernelSpec` — name, owning module, fit-guard, lowered-jnp twin
tolerance, and fallback story — the same single-source-of-truth
discipline the ``fuse/registry.py`` ``device_fn`` table applies to
fusible stages (``sntc_tpu.fuse.registry.device_kernels`` re-exports
this table as the kernel half of the capability registry).
``scripts/check_kernel_registry.py`` pins registry ⇔
docs/PERFORMANCE.md kernel-forge table ⇔ interpret-mode tests in
tier-1, both directions.

Selection and survival are shared, not per-kernel ad hoc:

* :func:`resolve_serve_kernels` is the one env switch for the serving
  tier — ``SNTC_SERVE_KERNELS`` = ``auto`` (pallas on TPU, off
  elsewhere) / ``pallas`` / ``interpret`` (the CPU tier-1 mode: every
  kernel runs through the Pallas interpreter) / ``off``.  The fit-side
  ``SNTC_TREE_HIST`` switch routes through :func:`resolve_impl` with
  its historical semantics intact (satellite: behavior-preserving).

* :func:`kernel_dispatch` is the poison/fallback ladder for host-level
  kernel calls: a fresh (kernel, signature) crosses the
  ``kernel.compile`` fault boundary; a compile failure — injected or
  genuine — poisons exactly that signature onto the XLA twin path and
  serves the batch there, so a kernel that cannot compile NEVER
  surfaces an error to the serving engine (zero quarantines, zero
  tenant strikes; the r18 platform-fault contract).  Under an active
  trace (a kernel embedded in a fused program) the decision is made at
  trace time and the in-flight kernel signatures are logged so
  ``FusedSegment.transform_async`` can poison them and recompile the
  SAME fused signature on the pure-XLA path when the enclosing compile
  fails (``sntc_tpu/fuse/planner.py``).

Every decision is counted in the catalogued ``sntc_kernel_*`` metric
family (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import os
import re
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

#: check_kernel_registry.py fails tier-1 when a Pallas call site
#: appears outside a registered kernel's module (or a registered
#: kernel's module has no Pallas call site)
_SERVE_ENV = "SNTC_SERVE_KERNELS"


@dataclass(frozen=True)
class KernelSpec:
    """One registered Pallas kernel (the docs kernel-forge table row)."""

    name: str
    #: repo-relative module holding the Pallas call site
    module: str
    #: fit-guard callable name (documented) + the guard itself
    guard_name: str
    guard: Callable[..., bool]
    #: documented pinning tolerance vs the lowered-jnp twin
    tolerance: str
    #: documented fallback path when the guard rejects / compile poisons
    fallback: str
    #: env switch that selects this kernel (shared or kernel-specific)
    env: str = _SERVE_ENV
    #: optional kernel-specific resolver (the tree_hist historical
    #: semantics); None = the shared serve-tier resolution
    resolver: Optional[Callable[..., str]] = None


_KERNELS: Dict[str, KernelSpec] = {}
_lock = threading.Lock()

# poison ledger: (kernel name, signature) pairs that failed to compile
# and serve the XLA twin forever after (cleared only by process restart
# — a kernel that cannot compile once will not compile again)
_poisoned: Dict[Tuple[str, Any], str] = {}
# fresh-signature ledger: the kernel.compile fault boundary fires once
# per (kernel, signature), exactly like predict.compile fires once per
# fresh row shape
_seen_sigs: set = set()
# trace-time kernel log (thread-local): kernels armed inside an active
# jit trace, so the fused-program compile-failure handler knows WHICH
# kernel signatures to poison before retrying on pure XLA
_trace_log = threading.local()


def register_kernel(spec: KernelSpec) -> KernelSpec:
    with _lock:
        _KERNELS[spec.name] = spec
    return spec


def _ensure_registered() -> None:
    """Import every kernel-bearing module so the registry is complete
    regardless of which subsystem imported first (the drift check and
    the docs table enumerate through this)."""
    import sntc_tpu.kernels.assemble  # noqa: F401
    import sntc_tpu.kernels.forest  # noqa: F401
    import sntc_tpu.ops.pallas_histogram  # noqa: F401


def registered_kernels() -> Dict[str, KernelSpec]:
    _ensure_registered()
    with _lock:
        return dict(_KERNELS)


def kernel_spec(name: str) -> KernelSpec:
    _ensure_registered()
    return _KERNELS[name]


# -- selection ---------------------------------------------------------------


def resolve_serve_kernels() -> str:
    """The serving-tier mode: ``pallas`` / ``interpret`` / ``off``.

    ``SNTC_SERVE_KERNELS`` = ``auto`` (default: pallas on a TPU default
    backend, off elsewhere — the CPU interpreter is a correctness tool,
    not a fast path), ``pallas`` (force), ``interpret`` (run every
    kernel through the Pallas interpreter — the tier-1 CPU mode), or
    ``off``."""
    raw = os.environ.get(_SERVE_ENV, "auto").strip().lower()
    if raw in ("off", "0", "none", "false"):
        return "off"
    if raw == "interpret":
        return "interpret"
    if raw in ("pallas", "on", "1", "true"):
        return "pallas"
    import jax

    return "pallas" if jax.default_backend() == "tpu" else "off"


def resolve_impl(name: str, **guard_kwargs) -> str:
    """Implementation selection for ``name`` through its registered
    resolver (the fit-side ``tree_hist`` keeps its historical
    ``SNTC_TREE_HIST`` semantics) or the shared serve-tier switch.
    Returns the impl token the caller dispatches on; every resolution
    is counted into the ``sntc_kernel_*`` family."""
    from sntc_tpu.obs.metrics import inc

    spec = kernel_spec(name)
    if spec.resolver is not None:
        impl = spec.resolver(**guard_kwargs)
        inc(
            "sntc_kernel_dispatch_total"
            if impl == "pallas" else "sntc_kernel_fallback_total",
            kernel=name,
            **({"impl": impl} if impl == "pallas" else {"reason": impl}),
        )
        return impl
    mode = resolve_serve_kernels()
    if mode == "off":
        inc("sntc_kernel_fallback_total", kernel=name, reason="off")
        return "xla"
    if not spec.guard(**guard_kwargs):
        inc("sntc_kernel_fallback_total", kernel=name, reason="guard")
        return "xla"
    return mode  # "pallas" | "interpret"


# -- the poison ladder -------------------------------------------------------


def poisoned(name: str, sig) -> bool:
    with _lock:
        return (name, sig) in _poisoned


def poison(name: str, sig, reason: str) -> bool:
    """Poison (kernel, signature) onto the XLA twin path; returns True
    when fresh.  Counted live in ``sntc_kernel_poisoned_signatures``
    and journaled as a structured event (never a tenant strike)."""
    from sntc_tpu.obs.metrics import set_gauge
    from sntc_tpu.resilience.policy import emit_event

    with _lock:
        fresh = (name, sig) not in _poisoned
        _poisoned[(name, sig)] = reason
        count = len(_poisoned)
    if fresh:
        try:
            set_gauge("sntc_kernel_poisoned_signatures", count)
        except Exception:
            pass
        emit_event(
            event="kernel_poisoned", component="model",
            site="kernel.compile", kernel=name, signature=repr(sig),
            reason=reason,
        )
    return fresh


def clear_poisons() -> None:
    """Test hook: forget every poisoned kernel signature."""
    from sntc_tpu.obs.metrics import set_gauge

    with _lock:
        _poisoned.clear()
        _seen_sigs.clear()
    try:
        set_gauge("sntc_kernel_poisoned_signatures", 0)
    except Exception:
        pass


def kernel_stats() -> dict:
    """Evidence snapshot for bench/fusion_stats: current mode plus the
    poison ledger."""
    with _lock:
        return {
            "mode": resolve_serve_kernels(),
            "poisoned_signatures": len(_poisoned),
            "poisoned": {
                f"{k}:{s}": r for (k, s), r in _poisoned.items()
            },
        }


def _under_trace(args) -> bool:
    import jax

    return any(isinstance(a, jax.core.Tracer) for a in args)


def begin_trace_capture() -> None:
    """Planner hook: start logging kernels armed inside the fused
    trace about to run on this thread."""
    _trace_log.entries = []


def traced_kernels() -> List[Tuple[str, Any]]:
    return list(getattr(_trace_log, "entries", []))


def poison_traced(reason: str) -> int:
    """Poison every kernel signature the current thread's last fused
    trace armed (the enclosing fused program failed to compile).
    Returns the number poisoned — 0 means no kernel was involved and
    the failure belongs to the fused program itself."""
    entries = traced_kernels()
    for name, sig in entries:
        poison(name, sig, reason)
    _trace_log.entries = []
    return len(entries)


def _note_trace(name: str, sig) -> None:
    entries = getattr(_trace_log, "entries", None)
    if entries is None:
        entries = _trace_log.entries = []
    entries.append((name, sig))


_PALLAS_COMPILE_RE = re.compile(
    r"interpret mode is supported|mosaic|pallas|tpu kernel compiler",
    re.IGNORECASE,
)


def classify_kernel_error(exc: Optional[BaseException]) -> Optional[str]:
    """Kernel-scope widening of ``classify_device_error``: inside the
    kernel tier's own dispatch (or a fused trace that armed kernels), a
    Pallas/Mosaic lowering failure is a compile error even when it is
    not XLA-runtime-shaped — e.g. the CPU backend raises a plain
    ``ValueError("Only interpret mode is supported on CPU backend.")``
    when ``SNTC_SERVE_KERNELS=pallas`` is forced off-TPU.  Such a
    failure must poison the signature and serve the twin, never strike
    the tenant.  The strict classifier keeps its shape rules for every
    other scope (a user ``ValueError`` mentioning "pallas" outside the
    kernel tier must never flip serving paths), which is why this
    widening lives here and not in ``resilience.device``."""
    from sntc_tpu.resilience.device import classify_device_error

    kind = classify_device_error(exc)
    if kind is not None:
        return kind
    seen = 0
    while exc is not None and seen < 8:
        if _PALLAS_COMPILE_RE.search(str(exc)):
            return "compile_error"
        exc = exc.__cause__ or exc.__context__
        seen += 1
    return None


def kernel_dispatch(
    name: str,
    kernel_fn: Callable[[str], Any],
    twin_fn: Callable[[], Any],
    *,
    signature,
    guard_kwargs: Optional[dict] = None,
):
    """Serve one kernel-tier call through the selection + poison
    ladder.  ``kernel_fn(impl)`` runs the Pallas path (``impl`` is
    ``"pallas"`` or ``"interpret"``); ``twin_fn()`` is the lowered-jnp
    XLA twin the kernel is pinned against (bitwise f64, ≤1e-5 rel f32 —
    docs/PERFORMANCE.md kernel-forge table).

    Host-level calls get the full try/poison/fallback arc: a compile
    failure (injected at ``kernel.compile`` or genuine) poisons exactly
    (kernel, signature) and serves THIS call on the twin — nothing
    escapes to the engine's strike ladder.  Calls under an active jit
    trace decide at trace time and log the armed signature for the
    planner's compile-failure handler; OOM/device-lost errors re-raise
    (they belong to the predictor's r18 response ladder, not the
    kernel tier)."""
    from sntc_tpu.obs.metrics import inc
    from sntc_tpu.resilience.faults import fault_point

    impl = resolve_impl(name, **(guard_kwargs or {}))
    if impl not in ("pallas", "interpret"):
        return twin_fn()
    if poisoned(name, signature):
        inc("sntc_kernel_fallback_total", kernel=name, reason="poisoned")
        return twin_fn()
    with _lock:
        fresh = (name, signature) not in _seen_sigs
        _seen_sigs.add((name, signature))
    traced = _under_trace(
        signature if isinstance(signature, (list, tuple)) else ()
    )
    # the kernel-compile fault boundary: fires once per fresh
    # (kernel, signature), exactly like predict.compile per row shape.
    # Under a trace this raises INTO the enclosing fused compile, where
    # the planner poisons the logged kernel and retries on pure XLA.
    try:
        if fresh:
            fault_point("kernel.compile")
        out = kernel_fn(impl)
    except Exception as e:
        kind = classify_kernel_error(e)
        if kind != "compile_error" or traced:
            raise
        poison(name, signature, repr(e))
        inc(
            "sntc_kernel_fallback_total", kernel=name,
            reason="compile_error",
        )
        return twin_fn()
    inc("sntc_kernel_dispatch_total", kernel=name, impl=impl)
    return out


def serve_kernel_call(
    name: str,
    args: tuple,
    kernel_fn: Callable[[str], Any],
    twin_fn: Callable[[], Any],
    *,
    static: tuple = (),
    guard_kwargs: Optional[dict] = None,
):
    """The model-serve entry: build the (shape, dtype, static) kernel
    signature from ``args`` — tracers and concrete arrays alike carry
    shape/dtype — then dispatch.  Inside a fused trace the decision is
    static per enclosing compile: log the armed kernel so a failed
    fused compile can poison it and retrace on the twin."""
    sig = tuple(
        (tuple(a.shape), str(getattr(a, "dtype", type(a).__name__)))
        for a in args
    ) + tuple(static)
    if _under_trace(args):
        impl = resolve_impl(name, **(guard_kwargs or {}))
        if impl not in ("pallas", "interpret") or poisoned(name, sig):
            return twin_fn()
        _note_trace(name, sig)
        with _lock:
            fresh = (name, sig) not in _seen_sigs
            _seen_sigs.add((name, sig))
        if fresh:
            from sntc_tpu.resilience.faults import fault_point

            fault_point("kernel.compile")
        from sntc_tpu.obs.metrics import inc

        inc("sntc_kernel_dispatch_total", kernel=name, impl=impl)
        return kernel_fn(impl)
    return kernel_dispatch(
        name, kernel_fn, twin_fn, signature=sig,
        guard_kwargs=guard_kwargs,
    )
