"""Estimator / Transformer / Pipeline — the user-facing capability surface.

Behavioral spec: Spark ML's pipeline abstractions (SURVEY.md §1 L1; upstream
``python/pyspark/ml/{base,pipeline}.py`` and
``mllib/.../org/apache/spark/ml/Pipeline.scala`` [U]):

  * ``Transformer.transform(frame) -> frame`` appends columns;
  * ``Estimator.fit(frame) -> Model`` learns and returns a fitted Transformer;
  * ``Pipeline`` chains stages: during ``fit``, transformers transform eagerly
    and estimators fit on the accumulated frame, producing a ``PipelineModel``
    of fitted stages (call-stack parity: SURVEY.md §3.1).

Unlike Spark there is no Py4J/JVM boundary (deleted per SURVEY.md §1 restack):
stages are plain Python objects whose numeric inner loops dispatch to JAX/XLA.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import NO_DEFAULT, Param, Params


class PipelineStage(Params):
    """Common base for Transformer and Estimator."""

    # the conventional input-column param names this base can discover;
    # stages reading columns through differently-named params MUST
    # override input_columns() so pipeline rewrites (sntc_tpu.fuse) and
    # the tuning prefix hoist see them
    _INPUT_COL_PARAMS = ("inputCol", "featuresCol", "inputCols")

    def input_columns(self) -> List[str]:
        """Column names this stage reads — at transform time for
        Transformers, at fit time for Estimators (unset params
        contribute nothing — an unset stage consumes nothing yet)."""
        out: List[str] = []
        for name in self._INPUT_COL_PARAMS:
            if not self.hasParam(name) or not self.isDefined(name):
                continue
            val = self.getOrDefault(name)
            if val is None:
                continue
            out.extend(val if isinstance(val, (list, tuple)) else [val])
        return out

    def save(self, path: str) -> str:
        """Persist this stage (SURVEY.md §5.4); see sntc_tpu.mlio."""
        from sntc_tpu.mlio import save_model

        return save_model(self, path)

    @classmethod
    def load(cls, path: str) -> "PipelineStage":
        from sntc_tpu.mlio import load_model

        obj = load_model(path)
        if not isinstance(obj, cls):
            raise TypeError(
                f"{path} holds a {type(obj).__name__}, not a {cls.__name__}"
            )
        return obj


class Transformer(PipelineStage):
    def transform(self, frame: Frame) -> Frame:
        raise NotImplementedError

    def transform_async(self, frame: Frame):
        """Dispatch this transform without blocking on device results.

        Returns a zero-arg ``finalize`` callable that materializes and
        returns the output Frame.  Device-backed models override this to
        dispatch their compute and defer host materialization, so a caller
        can overlap the NEXT batch's host work with this batch's device
        compute and transfer — the serving micro-batch pipeline ([B:11];
        JAX dispatch is asynchronous, only materialization blocks).  The
        default runs synchronously and is always correct.

        Thread contract (the pipelined engine relies on it): ``finalize``
        may be invoked from a DIFFERENT thread than the dispatching one —
        the overlapped retire stage materializes batch N on its delivery
        thread while the engine thread dispatches batch N+1 — and may be
        invoked MORE THAN ONCE (the engine's sink retry path re-invokes
        it per delivery attempt; the serving ``BatchPredictor`` memoizes,
        so engine deliveries materialize once, but a bare override must
        still tolerate re-invocation — re-materializing a jax.Array is
        fine).  Overrides must close over immutable per-call state only;
        mutating shared transformer state inside finalize is a data race.
        """
        out = self.transform(frame)
        return lambda: out

    def __call__(self, frame: Frame) -> Frame:
        return self.transform(frame)


class Estimator(PipelineStage):
    def fit(self, frame: Frame, params: Optional[Dict[str, Any]] = None) -> "Model":
        """Fit on ``frame``. ``params`` is a one-shot override map (Spark's
        ``fit(dataset, paramMap)`` convenience used by tuning)."""
        if params:
            return self.copy(params).fit(frame)
        return self._fit(frame)

    def _fit(self, frame: Frame) -> "Model":
        raise NotImplementedError


class Evaluator(PipelineStage):
    """Metric computer over a predictions Frame (Spark's
    ``ml/evaluation/Evaluator`` [U]).  A Params stage like every other
    pipeline piece, so tuning results persist/restore their evaluator
    spec (``CrossValidatorModel.save`` round-trips it)."""

    def evaluate(self, frame: Frame) -> float:
        raise NotImplementedError

    def isLargerBetter(self) -> bool:
        return True


class Model(Transformer):
    """A fitted Transformer produced by ``Estimator.fit``."""


class Pipeline(Estimator):
    """Chain of stages; ``fit`` returns a :class:`PipelineModel`.

    Spark semantics (SURVEY.md §3.1): stages before the last estimator are
    applied in order — transformers transform the running frame eagerly, each
    estimator is fit on the running frame and its fitted model then transforms
    the frame for downstream stages.
    """

    stages = Param("pipeline stages (Transformers and Estimators), applied in order")

    def __init__(self, stages: Optional[List[PipelineStage]] = None, **kwargs: Any):
        super().__init__(**kwargs)
        if stages is not None:
            self.set("stages", list(stages))

    def _fit(self, frame: Frame) -> "PipelineModel":
        stages = self.getStages()
        for stage in stages:
            if not isinstance(stage, (Transformer, Estimator)):
                raise TypeError(
                    f"pipeline stage {stage!r} is neither Transformer nor Estimator"
                )
        # Spark parity: only stages BEFORE the last estimator need to feed
        # transformed data downstream — the last estimator's model transform
        # over the training set would be discarded, so skip it.
        last_est = max(
            (i for i, s in enumerate(stages) if isinstance(s, Estimator)),
            default=-1,
        )
        fitted: List[Transformer] = []
        current = frame
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(current)
                fitted.append(model)
                if i < last_est:
                    current = model.transform(current)
            else:
                fitted.append(stage)
                if i < last_est:
                    current = stage.transform(current)
        return PipelineModel(stages=fitted)


class PipelineModel(Model):
    """Fitted pipeline: applies each fitted stage's transform in order."""

    stages = Param("fitted pipeline stages (all Transformers)")

    def __init__(self, stages: Optional[List[Transformer]] = None, **kwargs: Any):
        super().__init__(**kwargs)
        if stages is not None:
            self.set("stages", list(stages))

    def transform(self, frame: Frame) -> Frame:
        current = frame
        for stage in self.getStages():
            current = stage.transform(current)
        return current

    def transform_async(self, frame: Frame):
        """Host stages before the last device-dispatching stage run now;
        that stage's dispatch is deferred to its own ``transform_async``
        (feature prep for batch i+1 overlaps batch i's device compute in a
        pipelined serve loop), and trailing host-only stages (e.g.
        ``IndexToString`` on the prediction) run inside finalize."""
        stages = self.getStages()
        if not stages:
            return lambda: frame
        split = len(stages) - 1
        for i in reversed(range(len(stages))):
            if (
                type(stages[i]).transform_async
                is not Transformer.transform_async
            ):
                split = i
                break
        current = frame
        for stage in stages[:split]:
            current = stage.transform(current)
        fin = stages[split].transform_async(current)
        tail = stages[split + 1:]
        if not tail:
            return fin

        def finalize():
            out = fin()
            for stage in tail:
                out = stage.transform(out)
            return out

        return finalize
