from sntc_tpu.core.params import Param, Params, validators
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.base import (
    PipelineStage,
    Transformer,
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
)

__all__ = [
    "Param",
    "Params",
    "validators",
    "Frame",
    "PipelineStage",
    "Transformer",
    "Estimator",
    "Model",
    "Pipeline",
    "PipelineModel",
]
