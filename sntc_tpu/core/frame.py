"""Frame — the host-side columnar dataset (the DataFrame analog).

Replaces Spark SQL's DataFrame/Catalyst/Tungsten stack (SURVEY.md §1 L4) for
this framework's needs: an immutable, ordered collection of named numpy
columns.  Scalar columns are ``(N,)`` arrays; vector columns (the
``VectorAssembler`` output, Spark's ``VectorUDT`` analog) are ``(N, D)``
arrays.  pyarrow is the interchange format at the IO boundary (CSV/Parquet
ingest, Arrow RecordBatch streaming bridge — SURVEY.md §2.7 keeps Arrow C++ as
the host data plane).

Transformations return new Frames; column data is shared, never copied, unless
an op requires it — mirroring the immutability contract Spark's RDD model
provides (SURVEY.md §5.2).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Union

import numpy as np
import pyarrow as pa


ColumnLike = Union[np.ndarray, Sequence]


def object_column(values: Sequence) -> np.ndarray:
    """1-D object column of ragged values (token lists, itemsets).

    ``np.array(list_of_lists, dtype=object)`` silently builds a 2-D
    array when every inner list shares a length — the explicit fill
    keeps the column rank-1 regardless."""
    col = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        col[i] = v
    return col


# jax.Array, resolved lazily ONCE: _coerce_column runs per column on
# every Frame/with_column construction (the serving hot path builds
# several frames per micro-batch), and the per-call `import jax` it used
# to do costs a sys.modules lookup + attribute walk each time — while a
# module-level import would force jax into every Frame-only consumer
_JAX_ARRAY_TYPE = None


def _jax_array_type():
    global _JAX_ARRAY_TYPE
    if _JAX_ARRAY_TYPE is None:
        import jax

        _JAX_ARRAY_TYPE = jax.Array
    return _JAX_ARRAY_TYPE


def _coerce_column(name: str, value: ColumnLike):
    """Coerce one column to an array and validate its rank.

    jax.Array columns are held AS-IS: a device-resident column (e.g.
    StandardScalerModel's on-device output) flows to the next estimator
    without a host round trip; any numpy-only op falls back through
    ``__array__`` (which materializes).
    """
    # fast path: the overwhelmingly common case is an ndarray column —
    # no jax resolution, no isinstance against a lazily-imported type
    if isinstance(value, np.ndarray):
        arr = value
    elif isinstance(value, _jax_array_type()):
        arr = value
    else:
        arr = np.asarray(value)
    if arr.ndim not in (1, 2):
        raise ValueError(
            f"column {name!r} must be 1-D or 2-D, got shape {arr.shape}"
        )
    return arr


class Frame:
    """Immutable ordered mapping of column name -> numpy array.

    All columns share the same leading dimension (row count). 1-D columns are
    scalars, 2-D columns are fixed-width vectors.
    """

    __slots__ = ("_columns", "_num_rows")

    def __init__(self, columns: Mapping[str, ColumnLike]):
        cols: Dict[str, np.ndarray] = {}
        num_rows: Optional[int] = None
        for name, value in columns.items():
            arr = _coerce_column(name, value)
            if num_rows is None:
                num_rows = arr.shape[0]
            elif arr.shape[0] != num_rows:
                raise ValueError(
                    f"column {name!r} has {arr.shape[0]} rows, expected {num_rows}"
                )
            cols[name] = arr
        self._columns = cols
        self._num_rows = 0 if num_rows is None else int(num_rows)

    @classmethod
    def _wrap(cls, cols: Dict[str, np.ndarray], num_rows: int) -> "Frame":
        """Trusted constructor for derived frames whose columns were already
        validated by a prior ``__init__`` (select/drop/slice/... reuse or
        uniformly re-index them) — skips the per-column validation pass."""
        f = object.__new__(cls)
        f._columns = cols
        f._num_rows = num_rows
        return f

    # -- basic accessors -------------------------------------------------------

    @property
    def num_rows(self) -> int:
        return self._num_rows

    def __len__(self) -> int:
        return self._num_rows

    @property
    def columns(self) -> List[str]:
        return list(self._columns)

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def __getitem__(self, name: str) -> np.ndarray:
        try:
            return self._columns[name]
        except KeyError:
            raise KeyError(
                f"no column {name!r}; available: {list(self._columns)}"
            ) from None

    def column(self, name: str) -> np.ndarray:
        return self[name]

    @property
    def schema(self) -> Dict[str, tuple]:
        return {n: (a.dtype, a.shape[1:]) for n, a in self._columns.items()}

    # -- transformations (each returns a new Frame) ----------------------------

    def with_column(self, name: str, value: ColumnLike) -> "Frame":
        arr = _coerce_column(name, value)
        # a frame with rows (or columns) pins the row count; only a truly
        # empty frame (no columns, 0 rows) accepts any length
        if (self._columns or self._num_rows) and arr.shape[0] != self._num_rows:
            raise ValueError(
                f"column {name!r} has {arr.shape[0]} rows, expected "
                f"{self._num_rows}"
            )
        cols = dict(self._columns)
        cols[name] = arr
        return Frame._wrap(cols, int(arr.shape[0]))

    def select(self, names: Iterable[str]) -> "Frame":
        return Frame._wrap({n: self[n] for n in names}, self._num_rows)

    def drop(self, *names: str) -> "Frame":
        return Frame._wrap(
            {n: a for n, a in self._columns.items() if n not in names},
            self._num_rows,
        )

    def rename(self, mapping: Mapping[str, str]) -> "Frame":
        return Frame._wrap(
            {mapping.get(n, n): a for n, a in self._columns.items()},
            self._num_rows,
        )

    def filter(self, mask: np.ndarray) -> "Frame":
        mask = np.asarray(mask)
        if mask.dtype != np.bool_ or mask.shape != (self._num_rows,):
            raise ValueError("filter mask must be a boolean (N,) array")
        n = int(np.count_nonzero(mask))
        return Frame._wrap({k: a[mask] for k, a in self._columns.items()}, n)

    def take(self, indices: np.ndarray) -> "Frame":
        indices = np.asarray(indices)
        if indices.dtype == np.bool_:  # boolean masks select, not index
            return self.filter(indices)
        if indices.ndim != 1:
            raise ValueError(
                f"take() indices must be 1-D, got shape {indices.shape}"
            )
        return Frame._wrap(
            {n: a[indices] for n, a in self._columns.items()},
            int(indices.shape[0]),
        )

    def slice(self, start: int, stop: Optional[int] = None) -> "Frame":
        n = len(range(*slice(start, stop).indices(self._num_rows)))
        return Frame._wrap(
            {k: a[start:stop] for k, a in self._columns.items()}, n
        )

    def pad_rows(self, n_rows: int) -> "Frame":
        """Pad to ``n_rows`` by repeating the LAST row (shape-bucketed
        serving: micro-batches pad up to a power-of-two row bucket so the
        jitted predict compiles once per bucket, not once per batch
        shape).  The pad rows are copies of real data, so every row-wise
        stage stays numerically in-domain; callers track validity (the
        serving path threads a row-validity mask) and drop the tail after
        finalize."""
        if n_rows < self._num_rows:
            raise ValueError(
                f"pad_rows target {n_rows} < current {self._num_rows} rows"
            )
        if n_rows == self._num_rows:
            return self  # immutable — safe to share
        if self._num_rows == 0:
            raise ValueError("cannot pad an empty frame (no row to repeat)")
        pad = n_rows - self._num_rows
        cols: Dict[str, np.ndarray] = {}
        for name, a in self._columns.items():
            if not isinstance(a, np.ndarray):
                a = np.asarray(a)  # materialize device-resident columns
            tail = np.broadcast_to(a[-1:], (pad,) + a.shape[1:])
            cols[name] = np.concatenate([a, tail])
        return Frame._wrap(cols, int(n_rows))

    def fill_invalid_rows(self, valid: np.ndarray) -> "Frame":
        """Replace every row where ``valid`` is False with a copy of the
        nearest PRECEDING valid row (the first valid row for a leading
        invalid run; all-zeros/empty-string rows when no row is valid).

        The row-salvage counterpart of :meth:`pad_rows`: admission
        (``sntc_tpu.data.schema.SchemaContract``) excises poison rows
        via the serving row-validity mask WITHOUT changing the frame's
        shape — so the donor values only exist to keep device compute
        numerically in-domain and are dropped at finalize, exactly like
        bucket-padding rows."""
        valid = np.asarray(valid)
        if valid.dtype != np.bool_ or valid.shape != (self._num_rows,):
            raise ValueError(
                "fill_invalid_rows mask must be a boolean (N,) array"
            )
        if valid.all():
            return self  # immutable — safe to share
        n = self._num_rows
        if valid.any():
            # donor[i] = index of the nearest valid row at or before i
            # (leading invalid rows borrow the first valid row)
            idx = np.where(valid, np.arange(n), -1)
            donor = np.maximum.accumulate(idx)
            donor[donor < 0] = int(np.flatnonzero(valid)[0])
            return Frame._wrap(
                {name: a[donor] for name, a in self._columns.items()}, n
            )
        cols: Dict[str, np.ndarray] = {}
        for name, a in self._columns.items():
            if not isinstance(a, np.ndarray):
                a = np.asarray(a)
            if a.dtype.kind in "OUS":
                cols[name] = np.full(a.shape, "", dtype=a.dtype)
            else:
                cols[name] = np.zeros(a.shape, dtype=a.dtype)
        return Frame._wrap(cols, n)

    def concat(self, other: "Frame") -> "Frame":
        return Frame.concat_all([self, other])

    @classmethod
    def concat_all(cls, frames: Sequence["Frame"]) -> "Frame":
        """Concatenate many frames with one allocation per column (the
        all-days ingest path [B:10] concatenates 8 day files)."""
        if not frames:
            raise ValueError("concat_all requires at least one frame")
        first = frames[0]
        if len(frames) == 1:
            return first  # immutable — safe to share
        for f in frames[1:]:
            if f.columns != first.columns:
                raise ValueError("concat requires identical column sets/order")
        return cls(
            {
                n: np.concatenate([f._columns[n] for f in frames])
                for n in first.columns
            }
        )

    def random_split(
        self, weights: Sequence[float], seed: int = 0
    ) -> List["Frame"]:
        """Spark ``DataFrame.randomSplit`` analog: shuffled proportional split."""
        w = np.asarray(weights, dtype=np.float64)
        w = w / w.sum()
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self._num_rows)
        edges = np.floor(np.cumsum(w) * self._num_rows).astype(np.int64)
        edges[-1] = self._num_rows  # cumsum can underflow 1.0; never drop rows
        out, start = [], 0
        for stop in edges:
            out.append(self.take(perm[start:stop]))
            start = stop
        return out

    # -- Arrow interchange -----------------------------------------------------

    @classmethod
    def from_arrow(cls, table: Union[pa.Table, pa.RecordBatch]) -> "Frame":
        if isinstance(table, pa.RecordBatch):
            table = pa.Table.from_batches([table])
        if len(set(table.column_names)) != len(table.column_names):
            raise ValueError(
                "duplicate column names in Arrow table (deduplicate first, "
                f"e.g. at the CSV ingest layer): {table.column_names}"
            )
        cols: Dict[str, np.ndarray] = {}
        for name, col in zip(table.column_names, table.columns):
            if isinstance(col, pa.ChunkedArray):
                col = col.combine_chunks()
            if pa.types.is_fixed_size_list(col.type):
                width = col.type.list_size
                values = col.values.to_numpy(zero_copy_only=False)
                cols[name] = values.reshape(-1, width)
            else:
                cols[name] = col.to_numpy(zero_copy_only=False)
        return cls(cols)

    def to_arrow(self) -> pa.Table:
        arrays, names = [], []
        for name, arr in self._columns.items():
            if not isinstance(arr, np.ndarray):
                arr = np.asarray(arr)  # materialize device-resident columns
            if arr.ndim == 2:
                width = arr.shape[1]
                flat = pa.array(arr.reshape(-1))
                arrays.append(pa.FixedSizeListArray.from_arrays(flat, width))
            else:
                arrays.append(pa.array(arr))
            names.append(name)
        return pa.Table.from_arrays(arrays, names=names)

    @classmethod
    def from_pandas(cls, df) -> "Frame":
        return cls.from_arrow(pa.Table.from_pandas(df, preserve_index=False))

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def __repr__(self) -> str:
        cols = ", ".join(
            f"{n}:{a.dtype}{list(a.shape[1:])}" for n, a in self._columns.items()
        )
        return f"Frame[{self._num_rows} rows]({cols})"
