"""Typed Param system — the user-facing configuration surface of every stage.

Behavioral spec: Spark ML's Params system (SURVEY.md §5.6; upstream
``mllib/src/main/scala/org/apache/spark/ml/param/params.scala`` [U]): every
pipeline stage declares typed ``Param``s with defaults + validators, settable
per-instance, readable via generated ``get<Name>()`` accessors, documented via
``explainParams()``, and serialized with the model (sntc_tpu.mlio.save_load).

Differences from Spark (deliberate, TPU-native single-process design):
  * no JVM mirror — params live only on the Python stage object;
  * ``set<Name>()``/``setParams()`` return ``self`` for chaining, as in PySpark.
"""

from __future__ import annotations

import copy as _copy
import uuid
from typing import Any, Callable, Dict, Optional


class _NoDefault:
    """Sentinel for params with no default (must be set before use)."""

    _instance: Optional["_NoDefault"] = None

    def __new__(cls) -> "_NoDefault":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:  # pragma: no cover
        return "<undefined>"


NO_DEFAULT = _NoDefault()


class Param:
    """Descriptor declaring one typed parameter on a :class:`Params` subclass.

    Accessing the attribute on an *instance or class* returns the ``Param``
    object itself (PySpark convention: ``lr.maxIter`` is the Param; the value
    is read with ``lr.getMaxIter()`` / ``lr.getOrDefault("maxIter")``).
    """

    __slots__ = ("name", "doc", "default", "validator")

    def __init__(
        self,
        doc: str,
        default: Any = NO_DEFAULT,
        validator: Optional[Callable[[Any], bool]] = None,
        name: Optional[str] = None,
    ):
        self.name = name
        self.doc = doc
        self.default = default
        self.validator = validator

    def __set_name__(self, owner: type, name: str) -> None:
        if self.name is None:
            self.name = name

    def __get__(self, obj: Any, objtype: Optional[type] = None) -> "Param":
        return self

    def validate(self, value: Any) -> Any:
        if self.validator is not None and not self.validator(value):
            raise ValueError(
                f"Param {self.name}={value!r} failed validation: {self.doc}"
            )
        return value

    def __repr__(self) -> str:
        return f"Param(name={self.name!r})"


class validators:
    """Common Param validators (the ``ParamValidators`` analog [U])."""

    @staticmethod
    def gt(lower: float) -> Callable[[Any], bool]:
        return lambda v: v > lower

    @staticmethod
    def gteq(lower: float) -> Callable[[Any], bool]:
        return lambda v: v >= lower

    @staticmethod
    def in_range(lo: float, hi: float) -> Callable[[Any], bool]:
        return lambda v: lo <= v <= hi

    @staticmethod
    def one_of(*allowed: Any) -> Callable[[Any], bool]:
        return lambda v: v in allowed

    @staticmethod
    def is_bool() -> Callable[[Any], bool]:
        return lambda v: isinstance(v, bool)

    @staticmethod
    def list_of(elem_ok: Callable[[Any], bool]) -> Callable[[Any], bool]:
        return lambda v: isinstance(v, (list, tuple)) and all(elem_ok(e) for e in v)


def _capitalize(name: str) -> str:
    return name[0].upper() + name[1:]


class Params:
    """Base class giving subclasses Spark-style param handling.

    Subclasses declare class-level :class:`Param` attributes; ``get<Name>`` /
    ``set<Name>`` accessors are generated automatically. Constructor keyword
    arguments set params by name.
    """

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        # Walk the full MRO so Param declarations on plain mixin classes
        # (shared estimator/model param blocks) get accessors too.
        declared: Dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for name, p in vars(klass).items():
                if isinstance(p, Param):
                    declared[name] = p
        for name, p in declared.items():
            cap = _capitalize(name)
            getter_name, setter_name = f"get{cap}", f"set{cap}"
            # generate only when no accessor exists anywhere in the MRO —
            # hand-written overrides (and inherited generated ones, which
            # resolve by name) must not be shadowed
            if not hasattr(cls, getter_name):
                def _getter(self: "Params", _n: str = name) -> Any:
                    return self.getOrDefault(_n)
                _getter.__name__ = getter_name
                _getter.__doc__ = f"Value of param ``{name}``: {p.doc}"
                _getter._sntc_generated = True
                setattr(cls, getter_name, _getter)
            if not hasattr(cls, setter_name):
                def _setter(self: "Params", value: Any, _n: str = name) -> "Params":
                    return self.set(_n, value)
                _setter.__name__ = setter_name
                _setter.__doc__ = f"Set param ``{name}``: {p.doc}"
                _setter._sntc_generated = True
                setattr(cls, setter_name, _setter)

    def __init__(self, **kwargs: Any):
        self.uid = f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"
        self._paramMap: Dict[str, Any] = {}
        if kwargs:
            self.setParams(**kwargs)

    # -- declaration introspection -------------------------------------------

    @classmethod
    def params(cls) -> Dict[str, Param]:
        """All declared params, walking the MRO (subclass overrides win).

        Cached per class (the declaration set is fixed at class creation);
        callers must treat the returned dict as read-only.
        """
        cached = cls.__dict__.get("_sntc_params")
        if cached is None:
            cached = {}
            for klass in reversed(cls.__mro__):
                for name, p in vars(klass).items():
                    if isinstance(p, Param):
                        cached[name] = p
            cls._sntc_params = cached
        return cached

    def _param(self, param: Any) -> Param:
        if isinstance(param, Param):
            name = param.name
        else:
            name = param
        p = type(self).params().get(name)
        if p is None:
            raise AttributeError(f"{type(self).__name__} has no param {name!r}")
        return p

    # -- get / set ------------------------------------------------------------

    def set(self, param: Any, value: Any) -> "Params":
        p = self._param(param)
        self._paramMap[p.name] = p.validate(value)
        return self

    def setParams(self, **kwargs: Any) -> "Params":
        for name, value in kwargs.items():
            self.set(name, value)
        return self

    def getOrDefault(self, param: Any) -> Any:
        p = self._param(param)
        if p.name in self._paramMap:
            return self._paramMap[p.name]
        if p.default is NO_DEFAULT:
            raise KeyError(
                f"Param {p.name!r} of {type(self).__name__} has no default and "
                "was not set"
            )
        return p.default

    def isSet(self, param: Any) -> bool:
        return self._param(param).name in self._paramMap

    def isDefined(self, param: Any) -> bool:
        p = self._param(param)
        return p.name in self._paramMap or p.default is not NO_DEFAULT

    def hasParam(self, name: str) -> bool:
        return name in type(self).params()

    # -- documentation / serialization ----------------------------------------

    def explainParam(self, param: Any) -> str:
        p = self._param(param)
        default = "undefined" if p.default is NO_DEFAULT else repr(p.default)
        current = (
            repr(self._paramMap[p.name]) if p.name in self._paramMap else "default"
        )
        return f"{p.name}: {p.doc} (default: {default}, current: {current})"

    def explainParams(self) -> str:
        return "\n".join(
            self.explainParam(name) for name in sorted(type(self).params())
        )

    def paramValues(self, include_defaults: bool = True) -> Dict[str, Any]:
        """``{name: value}`` for every defined param — the save/load payload."""
        out: Dict[str, Any] = {}
        for name, p in type(self).params().items():
            if name in self._paramMap:
                out[name] = self._paramMap[name]
            elif include_defaults and p.default is not NO_DEFAULT:
                out[name] = p.default
        return out

    def copy(self, extra: Optional[Dict[str, Any]] = None) -> "Params":
        """Shallow-copy this stage, optionally overriding params (Spark
        ``copy(extra)`` semantics used by CrossValidator grid fits)."""
        new = _copy.copy(self)
        new._paramMap = dict(self._paramMap)
        if extra:
            for k, v in extra.items():
                new.set(k, v)
        return new

    def __repr__(self) -> str:
        parts = ", ".join(f"{k}={v!r}" for k, v in sorted(self._paramMap.items()))
        return f"{type(self).__name__}({parts})"
