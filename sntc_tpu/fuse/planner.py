"""Whole-pipeline fusion compiler — one device program per fusible run.

The serving hot path executed a fitted ``PipelineModel`` stage-by-stage:
every feature transformer round-tripped its output through a host numpy
column before the next stage ran — the ML-pipeline analog of the
per-operator interpretation Spark's whole-stage codegen eliminates
(SURVEY.md §2.6).  ``compile_pipeline`` compiles that interpretation
away:

1. **rewrite** — algebraic folds run first (``fuse.rules``: scaler →
   linear/MLP weight folding), shrinking the pipeline before fusion;
2. **partition** — the stage list splits into MAXIMAL runs of stages
   whose fitted instances export a pure device fn via the capability
   registry (``fuse.registry``); a classifier head with a packed device
   serve program terminates its run;
3. **compile** — each run becomes ONE :class:`FusedSegment`: a single
   jitted XLA program (per input signature; shape-bucketed serving keys
   it per bucket) with the host input columns as donated arguments, all
   intermediate columns living only in device registers/HBM, and ONE
   packed output per head.  Non-fusible stages (object/ragged columns,
   row-dropping ``handleInvalid='skip'``, data-dependent validation)
   stay eager between segments — the row-validity-mask contract of the
   shape-bucketed engine is untouched because row-dropping stages are
   never fused.  The ``VALID_COL`` mask column itself is never a plan
   read or write, so :class:`FusedSegment` carries it through verbatim
   (outputs layer onto the INPUT frame): bucket padding AND the r10
   admission layer's row salvage both compose with fusion — an excised
   row rides the fused program inside the batch's unchanged shape and
   is filtered only at the predictor's finalize, so ``compile_events``
   stays flat under salvage.

Evidence: every segment dispatch records its host→device uploads and
device→host materializations in the process transfer ledger
(``sntc_tpu.utils.profiling.transfer_ledger``); a fully-fused pipeline
serves each micro-batch with exactly ONE upload and ONE download
(journaled by bench config 6).

Scope notes: fused segments are a serving-time artifact — persist the
ORIGINAL fitted pipeline, not the compiled one.  Output frames omit
intermediate columns that exist only to feed a later fused stage
(pass ``keep=('col',)`` to retain one); every column a later eager
stage reads is kept automatically.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from sntc_tpu.core.base import PipelineModel, Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.kernels import registry as kreg
from sntc_tpu.obs import cost as obs_cost
from sntc_tpu.feature.vector_assembler import VectorAssembler
from sntc_tpu.fuse.registry import (
    F32_CAST,
    F32_ONLY,
    F64,
    DevicePlan,
    device_plan_for,
)
from sntc_tpu.fuse.rules import fold_scalers
from sntc_tpu.models.base import ClassificationModel
from sntc_tpu.obs.metrics import inc
from sntc_tpu.obs.trace import span
from sntc_tpu.resilience.device import (
    DeviceExecError,
    classify_device_error,
)
from sntc_tpu.resilience.faults import fault_point
from sntc_tpu.utils.profiling import active_ledgers


def _fusible_head(stage) -> bool:
    return isinstance(stage, ClassificationModel) and stage.has_device_serve()


class FusedSegment(Transformer):
    """One maximal fusible run compiled into a single device program.

    ``transform_async`` uploads the segment's external input columns
    (cast per each plan's declared policy — identical to the casts the
    staged path applies), dispatches ONE jitted program computing every
    fused stage plus the optional head's packed serve output, and
    returns a finalize that materializes the outputs into a Frame.
    Falls back to the eager stage-by-stage transform for empty frames
    and dtype-preserving stages bound to non-float32 columns
    (``fallbacks`` counts them).  Programs are cached per input
    signature — ``compile_events`` mirrors the BatchPredictor shape
    ledger, so shape-bucketed serving keeps it flat after warmup.
    """

    def __init__(
        self,
        stages: Sequence[Transformer],
        plans: Sequence[DevicePlan],
        head: Optional[ClassificationModel] = None,
        keep: Iterable[str] = (),
    ):
        super().__init__()
        if len(stages) != len(plans):
            raise ValueError("one DevicePlan per fused stage required")
        self._stages = list(stages)
        self._plans = list(plans)
        self._head = head
        self._keep = frozenset(keep)
        self._programs: dict = {}
        self._lock = threading.Lock()
        self.compile_events = 0  # distinct input signatures compiled
        self.invocations = 0  # fused dispatches
        self.fallbacks = 0  # eager fallbacks (empty/dtype-gated)
        # compute-plane fault domain (r18): set by
        # attach_device_domain (via BatchPredictor).  A compile failure
        # or watchdog breach poisons exactly (this segment, that input
        # signature) — later binds of the signature take the eager
        # host path while every other signature keeps compiling on
        # device; HOST_DEGRADED diverts ALL binds eagerly.
        self._domain = None
        self.segment_index: Optional[int] = None  # position in the plan
        self._poisoned: dict = {}  # signature -> reason
        self.poisoned_served = 0  # binds served off a poisoned signature
        # SNTC_OBS_COST_ANALYSIS=1: XLA cost_analysis() per compiled
        # signature (flops / bytes accessed), keyed by signature repr —
        # the device-cost side of the obs span correlation (extraction
        # shared with bench via obs.cost since r21)
        self.cost_analyses: dict = {}
        # per-signature measured wall time under the same hook:
        # sig repr -> [seconds, invocations], the roofline numerator
        self.cost_timings: dict = {}
        # per-SEGMENT transfer counters: fusion_stats() aggregates these
        # per model, so one engine's evidence is never polluted by other
        # fused models in the process (the global ledger stays the
        # process-wide view)
        self.uploads = 0
        self.downloads = 0

        # external inputs: the first consuming plan's read policy decides
        # the upload cast (in-segment columns arrive as device values).
        # Two plans reading ONE external column under DIFFERENT policies
        # cannot share a segment — the upload cast of one would bypass
        # the other's dtype guard and break the bitwise contract; the
        # planner splits such runs, and this constructor enforces it.
        external: List[Tuple[str, str]] = []
        produced: set = set()
        policies: dict = {}
        for plan in self._plans:
            for r in plan.reads:
                if r in produced:
                    continue
                if r not in policies:
                    policies[r] = plan.read_policy
                    external.append((r, plan.read_policy))
                elif policies[r] != plan.read_policy:
                    raise ValueError(
                        f"conflicting read policies for column {r!r} "
                        f"({policies[r]} vs {plan.read_policy}): split "
                        "these stages into separate segments"
                    )
            produced.update(plan.writes)
        if head is not None:
            # the head input is cast to float32 IN-PROGRAM (mirroring the
            # staged ClassificationModel.transform astype), so any upload
            # policy on an external features column is compatible
            fc = head.getFeaturesCol()
            if fc not in produced and fc not in policies:
                external.append((fc, F32_CAST))
        self._external = external

        # liveness: a written column whose FINAL value is only consumed
        # inside the segment is dead — it never leaves the device.  Leaf
        # outputs, `keep` columns, and anything a later pipeline stage
        # reads (folded into `keep` by compile_pipeline) materialize.
        write_order: List[str] = []
        last_writer: dict = {}
        for i, plan in enumerate(self._plans):
            for w in plan.writes:
                if w in write_order:
                    write_order.remove(w)
                write_order.append(w)
                last_writer[w] = i
        head_reads = {head.getFeaturesCol()} if head is not None else set()
        self._live_writes = [
            w
            for w in write_order
            if w in self._keep
            or not (
                w in head_reads
                or any(
                    w in self._plans[j].reads
                    for j in range(last_writer[w] + 1, len(self._plans))
                )
            )
        ]

    # -- introspection ------------------------------------------------------

    @property
    def fused_stages(self) -> List[Transformer]:
        """The original fitted stages this segment compiled (head last)."""
        out = list(self._stages)
        if self._head is not None:
            out.append(self._head)
        return out

    def input_columns(self) -> List[str]:
        return [name for name, _ in self._external]

    def __repr__(self) -> str:
        names = ", ".join(type(s).__name__ for s in self.fused_stages)
        return f"FusedSegment[{names}]"

    # -- execution ----------------------------------------------------------

    def _bind(self, frame: Frame) -> Optional[List[np.ndarray]]:
        """Host arrays for the program arguments, cast per policy;
        None when a dtype-preserving plan sees a non-float32 column
        (the eager path keeps the exact host semantics)."""
        args: List[np.ndarray] = []
        for name, policy in self._external:
            col = frame[name]
            if not isinstance(col, np.ndarray):
                col = np.asarray(col)  # device-resident column: materialize
            if policy == F32_ONLY:
                if col.dtype != np.float32:
                    return None
                args.append(col)
            elif policy == F64:
                args.append(np.asarray(col, np.float64))
            else:  # F32_CAST — the cast every fused stage applies itself
                args.append(col.astype(np.float32, copy=False))
        return args

    @staticmethod
    def _place_args(args: List[np.ndarray]) -> list:
        """Serve-mesh row placement (r22): with a serve mesh armed
        (``parallel.context.get_serve_mesh``), the dispatched batch rows
        split over the ``"data"`` axis by ``NamedSharding`` before the
        program call — the fused programs are purely row-wise, so GSPMD
        runs each shard on its own device and the gathered outputs are
        bitwise identical to the 1-device program.  Batches whose rows
        do not divide the mesh (only possible below the bucket floor)
        dispatch single-device unchanged, and a consistent placement
        policy keeps ONE compiled program per (signature, placement)."""
        from sntc_tpu.parallel.context import get_serve_mesh

        mesh = get_serve_mesh()
        if mesh is None or not args:
            return args
        from sntc_tpu.parallel.mesh import DATA_AXIS, data_sharding

        size = int(mesh.shape.get(DATA_AXIS, 1))
        n = int(args[0].shape[0])
        if size <= 1 or n == 0 or n % size:
            return args
        import jax

        return [
            jax.device_put(a, data_sharding(mesh, a.ndim)) for a in args
        ]

    @staticmethod
    def _signature(args: List[np.ndarray]):
        import jax

        # donation frees the uploaded input buffers for reuse by the
        # program's outputs; on CPU the backend ignores donation (and the
        # host buffer may be aliased zero-copy), so gate it off there
        donate = jax.default_backend() != "cpu"
        return (
            tuple((a.shape, a.dtype.str) for a in args),
            donate,
        )

    def _program(self, args: List[np.ndarray], sig=None):
        if sig is None:
            sig = self._signature(args)
        with self._lock:
            prog = self._programs.get(sig)
            if prog is not None:
                return prog
        import jax

        donate = sig[1]
        names = [n for n, _ in self._external]
        plans, head, live = self._plans, self._head, self._live_writes

        def run(*xs):
            import jax.numpy as jnp

            env = dict(zip(names, xs))
            for plan in plans:
                env.update(plan.apply(env))
            outs = []
            if head is not None:
                # the staged path's ClassificationModel.transform casts
                # features to float32 before predicting — replicate it,
                # or an x64-produced f64 feature column would run the
                # head in f64 and diverge from the staged output
                x = env[head.getFeaturesCol()].astype(jnp.float32)
                outs.append(head._predict_all_dev(x))
            outs.extend(env[w] for w in live)
            return tuple(outs)

        prog = jax.jit(
            run,
            donate_argnums=tuple(range(len(names))) if donate else (),
        )
        with self._lock:
            fresh = sig not in self._programs
            if fresh:
                self._programs[sig] = prog
                self.compile_events += 1
            prog = self._programs[sig]
        if fresh:
            inc("sntc_fuse_compile_events_total")
            if obs_cost.enabled():
                # device-cost hook (opt-in — it compiles the program
                # eagerly): XLA's own FLOPs/bytes estimate for this
                # signature, correlatable with the host fuse.* spans
                # and fed to the MFU/roofline plane (obs.cost)
                self.cost_analyses[repr(sig[0])] = obs_cost.extract(
                    prog, args
                )
        return prog

    def _transform_eager(self, frame: Frame) -> Frame:
        out = frame
        for stage in self._stages:
            out = stage.transform(out)
        if self._head is not None:
            out = self._head.transform(out)
        return out

    def transform(self, frame: Frame) -> Frame:
        return self.transform_async(frame)()

    def _eager_async(self, frame: Frame, poisoned: bool = False):
        """One eager fallback serve (the shared bookkeeping for the
        empty/dtype gate, poisoned signatures, and HOST_DEGRADED)."""
        self.fallbacks += 1
        inc("sntc_fuse_fallbacks_total")
        if poisoned:
            with self._lock:
                self.poisoned_served += 1
        out = self._transform_eager(frame)
        return lambda: out

    def _poison(self, sig, reason: str, site: str) -> None:
        with self._lock:
            fresh = sig not in self._poisoned
            self._poisoned[sig] = reason
        if fresh and self._domain is not None:
            self._domain.note_poisoned(
                site=site, signature=repr(sig[0]), reason=reason,
                segment=self.segment_index,
            )

    def transform_async(self, frame: Frame):
        args = self._bind(frame) if frame.num_rows else None
        if args is None:
            return self._eager_async(frame)
        dom = self._domain
        if dom is not None and dom.host_degraded:
            dom.note_fallback()
            return self._eager_async(frame)
        sig = self._signature(args)
        if sig in self._poisoned:
            if dom is not None:
                dom.note_fallback(poisoned=True)
            return self._eager_async(frame, poisoned=True)
        fresh = sig not in self._programs
        budget = dom.policy.compile_budget_s if dom is not None else None
        # snapshot the ledgers to record into AT DISPATCH TIME: the
        # engine scopes its own (per-tenant) ledger on its thread, and
        # the finalize closure below may run on the delivery thread —
        # capturing here keeps attribution correct across threads
        ledgers = active_ledgers()
        kreg.begin_trace_capture()  # kernels armed by THIS trace
        try:
            if fresh:
                # the DEVICE fault boundary for the fused-program
                # compile (chaos arms compile_error / kill here)
                fault_point("fuse.compile")
            t0 = time.perf_counter() if fresh else 0.0
            prog = self._program(args, sig)
            t_disp = (
                time.perf_counter() if obs_cost.enabled() else None
            )
            up_bytes = sum(a.nbytes for a in args)
            for led in ledgers:
                led.record_uploads(len(args), up_bytes)
            args_dev = self._place_args(args)
            with span("fuse.dispatch", args=len(args)):
                # async dispatch; finalize materializes.  For a fresh
                # signature THIS call triggers the XLA compile, so the
                # wall time below is the watchdog's compile measurement.
                outs = prog(*args_dev)
            if fresh and budget is not None:
                elapsed = time.perf_counter() - t0
                if elapsed > budget:
                    # the compile finished but blew the budget: a
                    # signature this expensive to (re)compile is a
                    # serving hazard — poison it and serve the host
                    # path, exactly like a failed compile.  The
                    # just-compiled executable is EVICTED too: a
                    # poisoned signature never binds again, so keeping
                    # it would pin dead device memory for the process
                    # lifetime
                    with self._lock:
                        self._programs.pop(sig, None)
                    self._poison(
                        sig,
                        f"compile watchdog: {elapsed:.3f}s > "
                        f"budget {budget}s",
                        site="fuse.compile",
                    )
                    if dom is not None:
                        dom.note_fallback(poisoned=True)
                    return self._eager_async(frame, poisoned=True)
        except Exception as e:
            kind = classify_device_error(e)
            # the kernel-scope classifier widens to Pallas/Mosaic
            # lowering failures that are not XLA-runtime-shaped (e.g.
            # pallas forced on a CPU backend); it only matters when
            # this trace actually armed kernels — poison_traced()
            # returns 0 otherwise and the strict ladder below rules
            if (
                kreg.classify_kernel_error(e) == "compile_error"
                and kreg.poison_traced(repr(e))
            ):
                # a Pallas kernel INSIDE this fused trace failed to
                # compile: the segment itself is healthy, so poison
                # exactly those kernel signatures (done above), evict
                # the half-built program, and recompile the SAME fused
                # signature — the retrace sees the poisoned kernels
                # and lowers their jnp twins instead.  The batch serves
                # on the XLA path, not the eager host path, and no
                # fault reaches the domain's strike ladder.
                with self._lock:
                    self._programs.pop(sig, None)
                return self.transform_async(frame)
            if dom is not None and kind == "compile_error":
                # poison exactly (this segment, this signature); other
                # signatures keep compiling on device
                self._poison(sig, repr(e), site="fuse.compile")
                dom.note_fault(kind, site="fuse.compile")
                dom.note_fallback(poisoned=True)
                return self._eager_async(frame, poisoned=True)
            raise  # OOM / device_lost respond at the predictor layer
        with self._lock:
            self.invocations += 1
            self.uploads += len(args)
        head, live = self._head, self._live_writes
        seg_index, sig_repr = self.segment_index, repr(sig[0])

        def finalize() -> Frame:
            try:
                with span("fuse.finalize"):
                    host = [np.asarray(o) for o in outs]
            except Exception as e:
                kind = classify_device_error(e)
                if kind is None:
                    raise
                # device-side materialization failure (overlap mode
                # surfaces these on the delivery thread): thread the
                # execution context — segment, signature — through the
                # error chain so the journaled evidence names the work
                # that died, not just the symptom (the engine adds the
                # batch id)
                raise DeviceExecError(
                    f"device {kind} while finalizing fused segment "
                    f"{seg_index} ({type(self).__name__}) signature "
                    f"{sig_repr}: {e}",
                    kind=kind, segment=seg_index, signature=sig_repr,
                ) from e
            down_bytes = sum(h.nbytes for h in host)
            for led in ledgers:
                led.record_downloads(len(host), down_bytes)
            with self._lock:
                self.downloads += len(host)
            if t_disp is not None:
                # dispatch -> host-materialized wall time: the roofline
                # numerator for this signature (obs.cost); gauges
                # update live so a scrape mid-serve sees current MFU
                dt = time.perf_counter() - t_disp
                with self._lock:
                    acc = self.cost_timings.setdefault(
                        sig_repr, [0.0, 0]
                    )
                    acc[0] += dt
                    acc[1] += 1
                    secs, inv = acc
                obs_cost.emit_mfu(
                    seg_index if seg_index is not None else 0,
                    obs_cost.roofline(
                        self.cost_analyses.get(sig_repr), secs, inv
                    ),
                )
            out_frame = frame
            feature_cols = host[1:] if head is not None else host
            for name, arr in zip(live, feature_cols):
                out_frame = out_frame.with_column(name, arr)
            if head is not None:
                packed = host[0]
                k = head.num_classes
                if head.getRawPredictionCol():
                    out_frame = out_frame.with_column(
                        head.getRawPredictionCol(), packed[:, :k]
                    )
                if head.getProbabilityCol():
                    out_frame = out_frame.with_column(
                        head.getProbabilityCol(), packed[:, k : 2 * k]
                    )
                if head.getPredictionCol():
                    out_frame = out_frame.with_column(
                        head.getPredictionCol(),
                        packed[:, 2 * k].astype(np.float64),
                    )
            return out_frame

        return finalize


def compile_pipeline(
    pipeline: PipelineModel,
    keep: Iterable[str] = (),
    fuse_heads: bool = True,
) -> PipelineModel:
    """Compile a fitted PipelineModel for serving: rewrite rules first
    (scaler folding), then each maximal run of registry-fusible stages
    (plus a terminating device-servable classifier head) becomes one
    :class:`FusedSegment`; everything else passes through eagerly.

    ``keep`` names intermediate columns to materialize even when only a
    fused stage consumes them; columns read by later eager stages are
    kept automatically.  ``fuse_heads=False`` restricts fusion to
    feature stages (the head stays a plain stage).
    """
    stages = fold_scalers(list(pipeline.getStages()))
    out: List[Transformer] = []
    i, n = 0, len(stages)
    while i < n:
        plan = device_plan_for(stages[i])
        if plan is None:
            out.append(stages[i])
            i += 1
            continue
        seg_stages: List[Transformer] = [stages[i]]
        seg_plans: List[DevicePlan] = [plan]
        seg_produced: set = set(plan.writes)
        seg_policies: dict = {
            r: plan.read_policy for r in plan.reads
        }
        i += 1
        while i < n:
            p = device_plan_for(stages[i])
            if p is None:
                break
            # a stage reading a shared EXTERNAL column under a different
            # upload policy than the run already requires would bypass
            # its own dtype guard (the first reader's cast wins at bind
            # time) — start a new segment instead, where the guard runs
            if any(
                r not in seg_produced
                and seg_policies.get(r, p.read_policy) != p.read_policy
                for r in p.reads
            ):
                break
            for r in p.reads:
                if r not in seg_produced:
                    seg_policies.setdefault(r, p.read_policy)
            seg_produced.update(p.writes)
            seg_stages.append(stages[i])
            seg_plans.append(p)
            i += 1
        head = None
        if fuse_heads and i < n and _fusible_head(stages[i]):
            head = stages[i]
            i += 1
        # single-upload rule: a fused VectorAssembler LEADING a segment
        # would turn the one packed upload into one upload per input
        # column — its host stack is the upload prep, so it runs eagerly
        while (
            seg_plans
            and isinstance(seg_stages[0], VectorAssembler)
            and len(seg_plans[0].reads) > 1
        ):
            out.append(seg_stages.pop(0))
            seg_plans.pop(0)
        if not seg_plans:
            if head is not None:
                out.append(head)
            continue
        later_reads = set(keep)
        for later in stages[i:]:
            later_reads.update(later.input_columns())
        seg = FusedSegment(
            seg_stages, seg_plans, head=head, keep=later_reads
        )
        # stable position among the plan's fused segments — the
        # execution context device-attributed errors carry (r18)
        seg.segment_index = sum(
            1 for s in out if isinstance(s, FusedSegment)
        )
        out.append(seg)
    return PipelineModel(stages=out)


def attach_device_domain(model, domain) -> int:
    """Hand a :class:`~sntc_tpu.resilience.device.DeviceFaultDomain`
    to every fused segment reachable from ``model`` (the
    BatchPredictor does this at construction and re-attaches on every
    hot-swap): segment-level compile failures then poison per
    (segment, signature) and HOST_DEGRADED diverts the fused programs
    to their eager path.  Returns the segment count."""
    segs = fused_segments(model)
    for i, seg in enumerate(segs):
        seg._domain = domain
        if seg.segment_index is None:
            seg.segment_index = i
    return len(segs)


def fused_segments(model) -> List[FusedSegment]:
    """Every FusedSegment reachable from ``model`` (PipelineModels are
    walked recursively; a BatchPredictor's wrapped model too)."""
    segs: List[FusedSegment] = []
    stack = [model]
    while stack:
        node = stack.pop()
        if isinstance(node, FusedSegment):
            segs.append(node)
        elif isinstance(node, PipelineModel):
            stack.extend(node.getStages())
        elif hasattr(node, "model") and isinstance(node.model, Transformer):
            stack.append(node.model)
    return segs


def fusion_stats(model) -> Optional[dict]:
    """Fusion evidence for ``pipeline_stats()``/bench: segment count,
    compile ledger, fallback count, and THIS model's transfer counters
    (per-segment sums — other fused models in the process don't leak
    in; the process-wide view lives in
    ``sntc_tpu.utils.profiling.transfer_ledger``).  None when the model
    contains no fused segment."""
    segs = fused_segments(model)
    if not segs:
        return None
    out = {
        "segments": len(segs),
        "fused_stages": sum(len(s.fused_stages) for s in segs),
        "compile_events": sum(s.compile_events for s in segs),
        "invocations": sum(s.invocations for s in segs),
        "fallbacks": sum(s.fallbacks for s in segs),
        "uploads": sum(s.uploads for s in segs),
        "downloads": sum(s.downloads for s in segs),
        "poisoned_signatures": sum(len(s._poisoned) for s in segs),
        "poisoned_served": sum(s.poisoned_served for s in segs),
    }
    # keyed per SEGMENT: two segments can compile identically-shaped
    # signatures, and a flat sig-keyed merge would attribute one
    # segment's device cost to the other
    costs = {
        f"segment{i}:{sig}": cost
        for i, s in enumerate(segs)
        for sig, cost in s.cost_analyses.items()
    }
    if costs:  # present only under SNTC_OBS_COST_ANALYSIS=1
        out["cost_analysis"] = costs
        roof = {}
        for i, s in enumerate(segs):
            for sig, cost in s.cost_analyses.items():
                secs, inv = s.cost_timings.get(sig, (0.0, 0))
                r = obs_cost.roofline(cost, secs, inv)
                if r is not None:
                    roof[f"segment{i}:{sig}"] = r
        if roof:
            out["roofline"] = roof
    from sntc_tpu.kernels.registry import kernel_stats

    out["kernels"] = kernel_stats()
    return out
