"""Capability registry — which fitted feature stages export a device fn.

The whole-pipeline fusion compiler (``sntc_tpu.fuse.planner``) can only
fuse a stage it can express as a PURE function of device arrays:
``apply(cols_in) -> cols_out`` with every parameter baked in at plan
time.  This module is the single source of truth for that capability:
each array-in/array-out feature transformer registers a *plan builder*
``(fitted stage) -> DevicePlan | None`` keyed on its EXACT class (a
subclass that overrides ``transform`` must register itself — MRO
matching would silently fuse semantics the subclass changed).

A builder returns ``None`` when THIS stage instance is non-fusible
(row-dropping ``handleInvalid='skip'``, data-dependent validation such
as ``handleInvalid='error'`` NaN checks or closed-ended Bucketizer
ranges, float64 math without ``jax_enable_x64``); the planner then
falls back to the stage's eager ``transform``, splitting the fused
segment — semantics are never approximated.

Bitwise contract: every ``apply`` replicates its stage's host
``transform`` arithmetic operation-for-operation (same casts, same
operation order) so the fused program is bitwise-equal to the staged
path — elementwise float32 ops are exact IEEE and matmuls reuse the
same jitted kernels the staged path dispatches.

Stages that cannot honor that contract stay off the registry and are
listed in the non-fusible table of ``docs/PERFORMANCE.md`` —
``scripts/check_fusible_stages.py`` (tier-1) asserts every feature
transformer is in exactly one of the two places.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

# read-binding policies: how the planner uploads an EXTERNAL host column
# this plan reads (in-segment columns arrive as device values already)
F32_CAST = "f32cast"  # host-cast to float32 first (the stage's own astype)
F32_ONLY = "f32only"  # dtype-preserving op: require float32, else fall back
F64 = "f64"  # float64 math — builders gate these on jax_enable_x64


class DevicePlan:
    """One fused stage: ``apply`` maps a dict of device columns to the
    stage's written columns, tracing exactly the host transform's math."""

    __slots__ = ("reads", "writes", "apply", "read_policy")

    def __init__(
        self,
        reads: List[str],
        writes: List[str],
        apply: Callable[[dict], dict],
        read_policy: str = F32_CAST,
    ):
        self.reads = list(reads)
        self.writes = list(writes)
        self.apply = apply
        self.read_policy = read_policy


_REGISTRY: Dict[type, Callable] = {}


def register_device_fn(cls: type):
    """Class decorator target: ``@register_device_fn(StageType)`` marks
    ``builder(stage) -> DevicePlan | None`` as StageType's exporter."""

    def deco(builder):
        _REGISTRY[cls] = builder
        return builder

    return deco


def registered_types() -> frozenset:
    return frozenset(_REGISTRY)


def device_plan_for(stage) -> Optional[DevicePlan]:
    """The stage's device plan, or None when it (or this configuration
    of it) must run eagerly.  Exact-type lookup, never MRO."""
    builder = _REGISTRY.get(type(stage))
    if builder is None:
        return None
    return builder(stage)


def _x64_enabled() -> bool:
    import jax

    return bool(jax.config.jax_enable_x64)


# ---------------------------------------------------------------------------
# plan builders
# ---------------------------------------------------------------------------
# Builders import their stage classes lazily-at-module-load (this module
# is imported by the planner, which serving already pays for); each
# closes over plain numpy constants so the traced fn embeds them as XLA
# constants — the fitted parameters ARE the program.


def _register_builtin() -> None:
    import jax.numpy as jnp

    from sntc_tpu.feature.chisq_selector import ChiSqSelectorModel
    from sntc_tpu.feature.dct import DCT, _dct_basis
    from sntc_tpu.feature.discretizers import Bucketizer
    from sntc_tpu.feature.encoders import ElementwiseProduct, VectorSlicer
    from sntc_tpu.feature.expansion import (
        Interaction,
        PolynomialExpansion,
        _expansion_plan,
    )
    from sntc_tpu.feature.pca import PCAModel
    from sntc_tpu.feature.scalers import (
        MaxAbsScalerModel,
        MinMaxScalerModel,
        RobustScalerModel,
    )
    from sntc_tpu.feature.standard_scaler import StandardScalerModel
    from sntc_tpu.feature.univariate_selector import (
        UnivariateFeatureSelectorModel,
    )
    from sntc_tpu.feature.variance_selector import (
        VarianceThresholdSelectorModel,
    )
    from sntc_tpu.feature.vector_assembler import VectorAssembler

    @register_device_fn(StandardScalerModel)
    def _standard_scaler(m):
        mu, f = m.affine()  # float64 single source of truth
        mu32, f32 = mu.astype(np.float32), f.astype(np.float32)
        with_mean, with_std = m.getWithMean(), m.getWithStd()
        inp, out = m.getInputCol(), m.getOutputCol()

        def apply(cols):
            x = cols[inp].astype(jnp.float32)
            if with_mean:
                x = x - jnp.asarray(mu32)[None, :]
            if with_std:
                x = x * jnp.asarray(f32)[None, :]
            return {out: x}

        return DevicePlan([inp], [out], apply)

    @register_device_fn(MinMaxScalerModel)
    def _minmax_scaler(m):
        lo, hi = m.originalMin, m.originalMax  # float32
        span = hi - lo
        out_lo, out_hi = float(m.getMin()), float(m.getMax())
        # identical constant arithmetic to the host transform (np.divide
        # with where; midpoint for constant features)
        scale = np.divide(
            out_hi - out_lo, span, out=np.zeros_like(span), where=span > 0
        )
        mid32 = np.float32(0.5 * (out_lo + out_hi))
        ok = span > 0
        inp, out = m.getInputCol(), m.getOutputCol()

        def apply(cols):
            x = cols[inp].astype(jnp.float32)
            scaled = (x - jnp.asarray(lo)[None, :]) * jnp.asarray(scale)[
                None, :
            ] + jnp.float32(out_lo)
            return {
                out: jnp.where(jnp.asarray(ok)[None, :], scaled, mid32)
            }

        return DevicePlan([inp], [out], apply)

    @register_device_fn(MaxAbsScalerModel)
    def _maxabs_scaler(m):
        inv = np.divide(
            1.0, m.maxAbs, out=np.zeros_like(m.maxAbs), where=m.maxAbs > 0
        )
        inp, out = m.getInputCol(), m.getOutputCol()

        def apply(cols):
            x = cols[inp].astype(jnp.float32)
            return {out: x * jnp.asarray(inv)[None, :]}

        return DevicePlan([inp], [out], apply)

    @register_device_fn(RobustScalerModel)
    def _robust_scaler(m):
        median = m.median  # float32
        inv = np.divide(
            1.0, m.range, out=np.zeros_like(m.range), where=m.range > 0
        )
        centering, scaling = m.getWithCentering(), m.getWithScaling()
        inp, out = m.getInputCol(), m.getOutputCol()

        def apply(cols):
            x = cols[inp].astype(jnp.float32)
            if centering:
                x = x - jnp.asarray(median)[None, :]
            if scaling:
                x = x * jnp.asarray(inv)[None, :]
            return {out: x}

        return DevicePlan([inp], [out], apply)

    @register_device_fn(PCAModel)
    def _pca(m):
        pc = m.pc  # [D, k] float32
        inp, out = m.getInputCol(), m.getOutputCol()

        def apply(cols):
            return {out: cols[inp].astype(jnp.float32) @ jnp.asarray(pc)}

        return DevicePlan([inp], [out], apply)

    @register_device_fn(DCT)
    def _dct(m):
        import jax

        inverse = bool(m.getInverse())
        inp, out = m.getInputCol(), m.getOutputCol()

        def apply(cols):
            x = cols[inp]
            if x.ndim != 2:  # trace-time shape check == eager ValueError
                raise ValueError("inputCol must be a vector column")
            basis = _dct_basis(x.shape[1], inverse)
            return {
                out: jnp.matmul(
                    x.astype(jnp.float32),
                    jnp.asarray(basis),
                    precision=jax.lax.Precision.HIGHEST,
                )
            }

        return DevicePlan([inp], [out], apply)

    @register_device_fn(ElementwiseProduct)
    def _elementwise_product(m):
        w = m.getScalingVec()
        if w is None:
            return None  # unset: the eager path raises the right error
        w32 = np.asarray(w, np.float32)
        inp, out = m.getInputCol(), m.getOutputCol()

        def apply(cols):
            x = cols[inp]
            if w32.shape != (x.shape[1],):
                raise ValueError(
                    f"scalingVec length {w32.shape[0]} != vector width "
                    f"{x.shape[1]}"
                )
            return {out: x * jnp.asarray(w32)[None, :]}

        # dtype-preserving on host (f64 in -> f64 out): fuse f32 only
        return DevicePlan([inp], [out], apply, read_policy=F32_ONLY)

    def _gather_plan(inp, out, idx):
        idx = np.asarray(idx, np.int64)

        def apply(cols):
            x = cols[inp]
            if len(idx) and (idx.min() < 0 or idx.max() >= x.shape[1]):
                raise ValueError(
                    f"indices out of range for vector width {x.shape[1]}"
                )
            return {out: jnp.take(x, jnp.asarray(idx), axis=1)}

        return DevicePlan([inp], [out], apply, read_policy=F32_ONLY)

    @register_device_fn(VectorSlicer)
    def _vector_slicer(m):
        idx = m.getIndices()
        if not idx:
            return None
        return _gather_plan(m.getInputCol(), m.getOutputCol(), idx)

    @register_device_fn(ChiSqSelectorModel)
    def _chisq_selector(m):
        return _gather_plan(
            m.getFeaturesCol(), m.getOutputCol(), m.selected_features
        )

    @register_device_fn(UnivariateFeatureSelectorModel)
    def _univariate_selector(m):
        return _gather_plan(
            m.getFeaturesCol(), m.getOutputCol(), m.selected_features
        )

    @register_device_fn(VarianceThresholdSelectorModel)
    def _variance_selector(m):
        return _gather_plan(
            m.getFeaturesCol(), m.getOutputCol(), m.selectedFeatures
        )

    @register_device_fn(VectorAssembler)
    def _vector_assembler(m):
        # 'error' needs a data-dependent NaN raise, 'skip' drops rows —
        # both are host semantics a pure device fn cannot express
        if m.getHandleInvalid() != "keep":
            return None
        ins = m.getInputCols()
        if not ins:
            return None
        out = m.getOutputCol()

        def apply(cols):
            parts = []
            for name in ins:
                c = cols[name].astype(jnp.float32)
                parts.append(c[:, None] if c.ndim == 1 else c)
            return {out: jnp.concatenate(parts, axis=1)}

        return DevicePlan(list(ins), [out], apply)

    @register_device_fn(PolynomialExpansion)
    def _poly_expansion(m):
        if not _x64_enabled():
            return None  # host math is float64; f32 would drift
        degree = int(m.getDegree())
        inp, out = m.getInputCol(), m.getOutputCol()

        def apply(cols):
            x = cols[inp]
            if x.ndim != 2:
                raise ValueError(
                    f"inputCol {inp!r} must be a vector column"
                )
            x = x.astype(jnp.float64)
            plan = _expansion_plan(x.shape[1], degree)
            outs = []
            for idxs in plan:
                col = x[:, idxs[0]]
                for i in idxs[1:]:  # same multiply order as the host loop
                    col = col * x[:, i]
                outs.append(col)
            return {out: jnp.stack(outs, axis=1)}

        return DevicePlan([inp], [out], apply, read_policy=F64)

    @register_device_fn(Interaction)
    def _interaction(m):
        if not _x64_enabled():
            return None
        names = m.getInputCols()
        if not names or len(names) < 2:
            return None
        out = m.getOutputCol()

        def apply(cols):
            mats = []
            for name in names:
                c = cols[name].astype(jnp.float64)
                mats.append(c[:, None] if c.ndim == 1 else c)
            acc = mats[0]
            for mat in mats[1:]:  # Spark foldRight: LAST varies fastest
                acc = (acc[:, :, None] * mat[:, None, :]).reshape(
                    acc.shape[0], -1
                )
            return {out: acc}

        return DevicePlan(list(names), [out], apply, read_policy=F64)

    @register_device_fn(Bucketizer)
    def _bucketizer(m):
        if not _x64_enabled():
            return None  # indices + comparisons are float64 on host
        if m.getInputCols():
            return None  # multi-column mode: eager (scope: scalar mode)
        if m.getHandleInvalid() != "keep":
            return None  # 'error' raises on NaN, 'skip' drops rows
        try:
            splits = m._splits()
        except ValueError:
            return None  # malformed splits: the eager path raises
        if not (np.isneginf(splits[0]) and np.isposinf(splits[-1])):
            # closed ends ALWAYS raise on out-of-range values (Spark
            # semantics) — a data-dependent check only the host can run
            return None
        n_buckets = len(splits) - 1
        inp, out = m.getInputCol(), m.getOutputCol()

        def apply(cols):
            v = cols[inp].astype(jnp.float64)
            idx = (
                jnp.searchsorted(
                    jnp.asarray(splits), v, side="right"
                ).astype(jnp.float64)
                - 1.0
            )
            idx = jnp.where(v == splits[-1], n_buckets - 1.0, idx)
            return {out: jnp.where(jnp.isnan(v), float(n_buckets), idx)}

        return DevicePlan([inp], [out], apply, read_policy=F64)


_register_builtin()


def device_kernels():
    """The hand-written kernel half of the device capability registry
    (r21): name -> :class:`sntc_tpu.kernels.registry.KernelSpec`.  The
    ``device_fn`` table above answers "which STAGES can fuse"; this one
    answers "which fused/serve OPS run a hand-written Pallas kernel",
    each with its fit-guard, pinning tolerance, and fallback path —
    see ``sntc_tpu/kernels/`` and the docs/PERFORMANCE.md kernel-forge
    table (``scripts/check_kernel_registry.py`` pins them together)."""
    from sntc_tpu.kernels.registry import registered_kernels

    return registered_kernels()
