"""Algebraic rewrite rules the fusion pass runs BEFORE partitioning.

Rule 1 (scaler folding, the r5 ``serve/fuse.py`` optimization promoted
to a planner rewrite): a ``StandardScalerModel`` feeding a linear head
(LogisticRegression) or an MLP first layer folds EXACTLY into the
head's weights:

    x' = (x - μ)·f        (f = 1/σ, 0 for constant features)
    x'W + b  =  x(f⊙W) + (b - (μ⊙f)W)

Folding beats fusing for these pairs — the scaler stage disappears
entirely instead of costing an elementwise pass inside the fused
program — so the planner applies it first and fuses whatever remains.
The scaler is dropped only when the head is its SOLE consumer; if any
later stage reads the scaled column the pair is left for the fusion
partitioner, which keeps the column alive.
"""

from __future__ import annotations

import numpy as np

from sntc_tpu.core.base import Transformer
from sntc_tpu.feature.standard_scaler import StandardScalerModel
from sntc_tpu.models.logistic_regression import LogisticRegressionModel
from sntc_tpu.models.mlp import (
    MultilayerPerceptronClassificationModel,
    _layer_sizes,
)


def _fold_into_lr(
    scaler: StandardScalerModel, model: LogisticRegressionModel
) -> LogisticRegressionModel:
    mu, f = scaler.affine()
    W = model.coefficientMatrix.astype(np.float64)  # [K, D]
    b = model.interceptVector.astype(np.float64)
    W2 = W * f[None, :]
    b2 = b - W2 @ mu
    folded = LogisticRegressionModel(
        coefficient_matrix=W2.astype(np.float32),
        intercepts=b2.astype(np.float32),
        is_binomial=model.is_binomial,
    )
    folded.setParams(**model.paramValues())
    folded.set("featuresCol", scaler.getInputCol())
    return folded


def _fold_into_mlp(
    scaler: StandardScalerModel, model: MultilayerPerceptronClassificationModel
) -> MultilayerPerceptronClassificationModel:
    mu, f = scaler.affine()
    layers = tuple(int(v) for v in model.getLayers())
    d_in, d_h = _layer_sizes(layers)[0]
    theta = model.weights.astype(np.float64).copy()
    W1 = theta[: d_in * d_h].reshape(d_in, d_h)
    b1 = theta[d_in * d_h : d_in * d_h + d_h]
    W1_new = f[:, None] * W1
    b1_new = b1 - (mu * f) @ W1
    theta[: d_in * d_h] = W1_new.reshape(-1)
    theta[d_in * d_h : d_in * d_h + d_h] = b1_new
    folded = MultilayerPerceptronClassificationModel(
        weights=theta.astype(np.float32), layers=list(layers)
    )
    folded.setParams(**{
        k: v for k, v in model.paramValues().items() if k != "layers"
    })
    folded.set("featuresCol", scaler.getInputCol())
    return folded


_FOLDABLE = {
    LogisticRegressionModel: _fold_into_lr,
    MultilayerPerceptronClassificationModel: _fold_into_mlp,
}


def _consumes(stage: Transformer, col: str) -> bool:
    # total, not heuristic: Transformer.input_columns() covers the standard
    # input params and is overridable by stages with nonstandard ones
    return col in stage.input_columns()


def fold_scalers(stages: list) -> list:
    """Apply rule 1 over a fitted stage list; non-matching patterns pass
    through untouched.  Returns a NEW list (input never mutated)."""
    out: list = []
    i = 0
    while i < len(stages):
        s = stages[i]
        nxt = stages[i + 1] if i + 1 < len(stages) else None
        fold = _FOLDABLE.get(type(nxt)) if nxt is not None else None
        if (
            isinstance(s, StandardScalerModel)
            and fold is not None
            and nxt.getFeaturesCol() == s.getOutputCol()
            and not any(
                _consumes(later, s.getOutputCol()) for later in stages[i + 2:]
            )
        ):
            out.append(fold(s, nxt))
            i += 2
        else:
            out.append(s)
            i += 1
    return out
