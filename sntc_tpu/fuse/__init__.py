"""Whole-pipeline fusion — one jitted device program per fusible run.

Public surface:

* :func:`compile_pipeline` — compile a fitted ``PipelineModel`` for
  serving (rewrite rules + maximal-segment fusion + head packing);
* :func:`compile_serving` — the r5 entry point, now a thin alias of
  ``compile_pipeline`` (scaler folding became rewrite rule 1);
* :class:`FusedSegment` / :func:`fused_segments` / :func:`fusion_stats`
  — the compiled artifact and its evidence counters;
* :func:`register_device_fn` / :func:`device_plan_for` /
  :func:`registered_types` — the capability registry
  (``scripts/check_fusible_stages.py`` audits it against the
  non-fusible table in ``docs/PERFORMANCE.md``).
"""

from sntc_tpu.fuse.planner import (
    FusedSegment,
    attach_device_domain,
    compile_pipeline,
    fused_segments,
    fusion_stats,
)
from sntc_tpu.fuse.registry import (
    DevicePlan,
    device_plan_for,
    register_device_fn,
    registered_types,
)
from sntc_tpu.fuse.rules import fold_scalers

# the r5 serving entry point, kept as an alias: "compile for serving"
# now means the full fusion pass (fold + partition + jit)
compile_serving = compile_pipeline

__all__ = [
    "DevicePlan",
    "FusedSegment",
    "attach_device_domain",
    "compile_pipeline",
    "compile_serving",
    "device_plan_for",
    "fold_scalers",
    "fused_segments",
    "fusion_stats",
    "register_device_fn",
    "registered_types",
]
