from sntc_tpu.app import main

raise SystemExit(main())
