"""RankingEvaluator + MultilabelClassificationEvaluator (Spark 3.0).

Behavioral spec: upstream ``ml/evaluation/{RankingEvaluator,
MultilabelClassificationEvaluator}.scala`` →
``mllib/evaluation/{RankingMetrics,MultilabelMetrics}.scala`` [U].

RankingEvaluator (prediction = ranked id array, label = relevant id
set):

  * ``meanAveragePrecision``: mean over queries of
    ``Σ_hits precision@hit / |relevant|``;
  * ``meanAveragePrecisionAtK``: the same sum truncated at k, divided by
    ``min(|relevant|, k)`` (mllib's ``averagePrecisionAtK``);
  * ``precisionAtK``: ``#relevant in first k / k`` (k fixed, short lists
    count misses — mllib semantics);
  * ``recallAtK``: ``#relevant in first k / |relevant|``;
  * ``ndcgAtK``: binary-relevance DCG with ``1/log2(i+2)`` gains against
    the ideal prefix, mllib's form.

MultilabelClassificationEvaluator (prediction and label both label-set
arrays): subsetAccuracy, accuracy (Jaccard mean; a both-empty row is
0/0 = NaN and poisons the mean, exactly as Spark's bare division does),
hammingLoss (universe = distinct values of the LABEL column, mllib's
``numLabels``), document-averaged precision/recall/f1 (the mllib
defaults), plus ``microPrecision``/``microRecall``/``microF1Measure``
over global true/false positive counts.

Host-side: set arithmetic over ragged id arrays — no dense kernel
(SURVEY.md §2.4's "on host" rule).
"""

from __future__ import annotations

import numpy as np

from sntc_tpu.core.base import Evaluator
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


class RankingEvaluator(Evaluator):
    _METRICS = (
        "meanAveragePrecision",
        "meanAveragePrecisionAtK",
        "precisionAtK",
        "ndcgAtK",
        "recallAtK",
    )

    metricName = Param("ranking metric", default="meanAveragePrecision",
                       validator=validators.one_of(*_METRICS))
    predictionCol = Param("ranked predicted-id array column",
                          default="prediction")
    labelCol = Param("relevant-id array column", default="label")
    k = Param("cutoff for the @K metrics", default=10,
              validator=validators.gt(0))

    def evaluate(self, frame: Frame) -> float:
        metric = self.getMetricName()
        k = int(self.getK())
        preds = frame[self.getPredictionCol()]
        labels = frame[self.getLabelCol()]
        vals = []
        for p, l in zip(preds, labels):
            p = list(p)
            rel = set(l)
            if metric == "meanAveragePrecision":
                vals.append(self._avg_precision(p, rel, None))
            elif metric == "meanAveragePrecisionAtK":
                vals.append(self._avg_precision(p, rel, k))
            elif metric == "precisionAtK":
                hits = sum(1 for x in p[:k] if x in rel)
                vals.append(hits / k)
            elif metric == "recallAtK":
                hits = sum(1 for x in p[:k] if x in rel)
                vals.append(hits / max(len(rel), 1))
            else:  # ndcgAtK
                vals.append(self._ndcg(p, rel, k))
        return float(np.mean(vals)) if vals else 0.0

    @staticmethod
    def _avg_precision(p, rel, k) -> float:
        if not rel:
            return 0.0
        cut = p if k is None else p[:k]
        hits, score = 0, 0.0
        for i, x in enumerate(cut):
            if x in rel:
                hits += 1
                score += hits / (i + 1)
        denom = len(rel) if k is None else min(len(rel), k)
        return score / denom

    @staticmethod
    def _ndcg(p, rel, k) -> float:
        if not rel:
            return 0.0
        dcg = sum(
            1.0 / np.log2(i + 2) for i, x in enumerate(p[:k]) if x in rel
        )
        ideal = sum(
            1.0 / np.log2(i + 2) for i in range(min(len(rel), k))
        )
        return float(dcg / ideal)


class MultilabelClassificationEvaluator(Evaluator):
    _METRICS = (
        "subsetAccuracy",
        "accuracy",
        "hammingLoss",
        "precision",
        "recall",
        "f1Measure",
        "microPrecision",
        "microRecall",
        "microF1Measure",
    )

    metricName = Param("multilabel metric", default="f1Measure",
                       validator=validators.one_of(*_METRICS))
    predictionCol = Param("predicted label-set array column",
                          default="prediction")
    labelCol = Param("true label-set array column", default="label")

    def isLargerBetter(self) -> bool:
        return self.getMetricName() != "hammingLoss"

    def evaluate(self, frame: Frame) -> float:
        metric = self.getMetricName()
        preds = [set(v) for v in frame[self.getPredictionCol()]]
        labels = [set(v) for v in frame[self.getLabelCol()]]
        n = len(preds)
        if n == 0:
            return 0.0
        if metric == "subsetAccuracy":
            return float(np.mean([p == l for p, l in zip(preds, labels)]))
        if metric == "accuracy":
            # Spark MultilabelMetrics.accuracy is the mean Jaccard with a
            # bare 0/0 division: a row where BOTH sets are empty yields
            # NaN and poisons the mean — parity means reproducing that,
            # not repairing it (the former 1.0 repair was the last
            # documented evaluator delta, closed r5)
            return float(np.mean([
                len(p & l) / len(p | l) if (p or l) else float("nan")
                for p, l in zip(preds, labels)
            ]))
        if metric == "hammingLoss":
            # Spark's numLabels is the distinct count over the LABEL
            # column only (MultilabelMetrics.labels [U])
            universe = set().union(*labels) if labels else set()
            width = max(len(universe), 1)
            return float(
                sum(len(p ^ l) for p, l in zip(preds, labels))
                / (n * width)
            )
        if metric in ("precision", "recall", "f1Measure"):
            # mllib document-averaged forms
            if metric == "precision":
                return float(np.mean([
                    len(p & l) / max(len(p), 1) for p, l in zip(preds, labels)
                ]))
            if metric == "recall":
                return float(np.mean([
                    len(p & l) / max(len(l), 1) for p, l in zip(preds, labels)
                ]))
            return float(np.mean([
                2.0 * len(p & l) / max(len(p) + len(l), 1)
                for p, l in zip(preds, labels)
            ]))
        tp = sum(len(p & l) for p, l in zip(preds, labels))
        fp = sum(len(p - l) for p, l in zip(preds, labels))
        fn = sum(len(l - p) for p, l in zip(preds, labels))
        if metric == "microPrecision":
            return float(tp / max(tp + fp, 1))
        if metric == "microRecall":
            return float(tp / max(tp + fn, 1))
        return float(2.0 * tp / max(2 * tp + fp + fn, 1))
