"""ClusteringEvaluator — silhouette.

Behavioral spec: upstream ``ml/evaluation/ClusteringEvaluator.scala``
[U]: ``metricName='silhouette'`` with ``distanceMeasure``
squaredEuclidean (default) | cosine, computed with Spark's O(N·k)
closed form — per-cluster (count, Σx, Σ‖x‖²) statistics give every
point's mean distance to every cluster without any pairwise pass:

  Σ_q∈c ‖p − q‖² = n_c‖p‖² − 2 p·Σx_c + Σ‖x‖²_c

``a(i)`` divides by ``n_c − 1`` (own cluster, excluding the point —
Spark's raw ``averageDistanceToCluster`` divides by ``n_c``, but its
``pointSilhouetteCoefficient`` then multiplies by ``n_c/(n_c−1)``, so
the two agree; see docs/PARITY.md for the denominator note);
``b(i)`` is the min over other clusters of the mean; singleton clusters
score 0; the metric is the unweighted mean of ``(b−a)/max(a,b)``.
``isLargerBetter`` is True.

Host-side: the only non-trivial op is one ``[N, k]`` matmul.
"""

from __future__ import annotations

import numpy as np

from sntc_tpu.core.base import Evaluator
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


def _silhouette(X, labels, k, cosine):
    n = len(labels)
    if k < 2:
        raise ValueError("silhouette requires at least 2 clusters")
    if cosine:
        norms = np.linalg.norm(X, axis=1, keepdims=True)
        X = X / np.maximum(norms, 1e-12)
    counts = np.bincount(labels, minlength=k).astype(np.float64)
    sums = np.zeros((k, X.shape[1]), np.float64)
    np.add.at(sums, labels, X)
    if cosine:
        # mean cosine distance from p to cluster c: 1 − p·Σx̂_c / n_c
        cross = X @ sums.T  # [N, k]
        mean_d = 1.0 - cross / np.maximum(counts, 1.0)[None, :]
        own_excl = np.maximum(counts - 1.0, 1.0)
        # own cluster, excluding self (self cosine distance is 0):
        # (n_c·mean − 0) / (n_c − 1)
        own_sum = counts[labels] * mean_d[np.arange(n), labels]
        a = own_sum / own_excl[labels]
    else:
        sqn = (X**2).sum(axis=1)
        sq_sums = np.zeros(k, np.float64)
        np.add.at(sq_sums, labels, sqn)
        cross = X @ sums.T
        # Σ_q∈c ‖p−q‖² for every (point, cluster)
        tot = (
            counts[None, :] * sqn[:, None]
            - 2.0 * cross
            + sq_sums[None, :]
        )
        mean_d = tot / np.maximum(counts, 1.0)[None, :]
        own_excl = np.maximum(counts - 1.0, 1.0)
        a = tot[np.arange(n), labels] / own_excl[labels]
    other = mean_d.copy()
    other[np.arange(n), labels] = np.inf
    # empty cluster ids (never predicted) must not contribute a fake
    # zero distance: Spark iterates only over occurring clusters
    other[:, counts == 0] = np.inf
    b = other.min(axis=1)
    s = np.where(
        counts[labels] <= 1.0,
        0.0,
        (b - a) / np.maximum(np.maximum(a, b), 1e-12),
    )
    return float(s.mean())


class ClusteringEvaluator(Evaluator):
    _METRICS = ("silhouette",)

    metricName = Param("metric to compute", default="silhouette",
                       validator=validators.one_of(*_METRICS))
    featuresCol = Param("feature vector column", default="features")
    predictionCol = Param("cluster-id column", default="prediction")
    distanceMeasure = Param(
        "squaredEuclidean | cosine", default="squaredEuclidean",
        validator=validators.one_of("squaredEuclidean", "cosine"),
    )

    def evaluate(self, frame: Frame) -> float:
        X = np.asarray(frame[self.getFeaturesCol()], np.float64)
        labels = np.asarray(frame[self.getPredictionCol()], np.int64)
        k = int(labels.max()) + 1 if len(labels) else 0
        return _silhouette(
            X, labels, k, self.getDistanceMeasure() == "cosine"
        )
