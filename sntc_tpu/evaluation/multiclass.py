"""Multiclass metrics — EXACT Spark MulticlassMetrics semantics.

Behavioral spec: SURVEY.md §2.4 (upstream
``ml/evaluation/MulticlassClassificationEvaluator.scala`` +
``mllib/evaluation/MulticlassMetrics.scala`` [U]).  Parity notes that
macro-F1 claims die on (SURVEY.md §7.2 item 3):

  * Spark's evaluator ``metricName="f1"`` is the **weighted** F-measure
    (class-frequency weighted), not macro;
  * [B:2]'s metric of record is **macro-F1** — the unweighted mean of
    per-class F1 — exposed here as ``metricName="macroF1"``;
  * every ratio uses the 0/0 -> 0 convention;
  * weights are by TRUE-label frequency; per-class stats cover every class
    seen in labels or predictions.

The confusion matrix reduces on-device (``segment_sum`` + ``psum`` over the
mesh — SURVEY.md §2.4 "TPU equiv"); the scalar metrics are host arithmetic.
"""

from __future__ import annotations

import jax
import numpy as np

from sntc_tpu.core.base import Evaluator
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.parallel.collectives import (
    make_tree_aggregate,
    shard_batch,
    shard_weights,
)
from sntc_tpu.parallel.context import get_default_mesh

from functools import lru_cache


@lru_cache(maxsize=None)
def _confusion_agg(mesh, k: int):
    """One compiled confusion-matrix program per (mesh, num_classes)
    across all evaluations (a rebuilt aggregate recompiles per call)."""

    def conf(ys, ps, ws):
        return jax.ops.segment_sum(ws, ys * k + ps, num_segments=k * k)

    return make_tree_aggregate(conf, mesh)


class MulticlassMetrics:
    """Confusion-matrix metrics for (prediction, label) pairs.

    ``confusion[i, j]`` counts rows with true label ``i`` predicted ``j``
    (Spark's ``confusionMatrix`` orientation).
    """

    def __init__(
        self,
        labels: np.ndarray,
        predictions: np.ndarray,
        weights: np.ndarray = None,
        num_classes: int = None,
        mesh=None,
    ):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        y = labels.astype(np.int32)
        p = predictions.astype(np.int32)
        if num_classes is None:
            num_classes = int(max(y.max(initial=0), p.max(initial=0))) + 1
        k = int(num_classes)
        w = (
            np.ones(len(y), np.float32)
            if weights is None
            else np.asarray(weights, np.float32)
        )

        mesh = mesh or get_default_mesh()
        ys, ps, _ = shard_batch(mesh, y, p)
        ws = shard_weights(mesh, w, ys.shape[0])

        flat = _confusion_agg(mesh, k)(ys, ps, ws)
        self.confusion = np.asarray(flat, np.float64).reshape(k, k)
        self.num_classes = k

    # -- per-class arrays (index = class id) ----------------------------------

    @property
    def true_positives(self) -> np.ndarray:
        return np.diag(self.confusion)

    @property
    def label_counts(self) -> np.ndarray:
        return self.confusion.sum(axis=1)

    @property
    def prediction_counts(self) -> np.ndarray:
        return self.confusion.sum(axis=0)

    @staticmethod
    def _safe_div(a: np.ndarray, b: np.ndarray) -> np.ndarray:
        return np.divide(a, b, out=np.zeros_like(a, dtype=np.float64), where=b != 0)

    def precision_by_label(self) -> np.ndarray:
        return self._safe_div(self.true_positives, self.prediction_counts)

    def recall_by_label(self) -> np.ndarray:
        return self._safe_div(self.true_positives, self.label_counts)

    # Spark aliases: TPR == recall
    true_positive_rate_by_label = recall_by_label

    def false_positive_rate_by_label(self) -> np.ndarray:
        """FP / negatives per class (Spark ``falsePositiveRateByLabel``)."""
        fp = self.prediction_counts - self.true_positives
        negatives = self.confusion.sum() - self.label_counts
        return self._safe_div(fp, negatives)

    def f_measure_by_label(self, beta: float = 1.0) -> np.ndarray:
        p, r = self.precision_by_label(), self.recall_by_label()
        b2 = beta * beta
        return self._safe_div((1 + b2) * p * r, b2 * p + r)

    # -- scalar metrics -------------------------------------------------------

    @property
    def accuracy(self) -> float:
        total = self.confusion.sum()
        return float(self.true_positives.sum() / total) if total else 0.0

    def _weights(self) -> np.ndarray:
        counts = self.label_counts
        total = counts.sum()
        return counts / total if total else counts

    def weighted_precision(self) -> float:
        return float((self._weights() * self.precision_by_label()).sum())

    def weighted_recall(self) -> float:
        return float((self._weights() * self.recall_by_label()).sum())

    def weighted_f_measure(self, beta: float = 1.0) -> float:
        return float((self._weights() * self.f_measure_by_label(beta)).sum())

    def weighted_true_positive_rate(self) -> float:
        return self.weighted_recall()

    def weighted_false_positive_rate(self) -> float:
        return float(
            (self._weights() * self.false_positive_rate_by_label()).sum()
        )

    def hamming_loss(self) -> float:
        """Misclassification fraction (single-label: 1 − accuracy)."""
        total = self.confusion.sum()
        if not total:
            return 0.0
        return float((total - self.true_positives.sum()) / total)

    def macro_f1(self) -> float:
        """Unweighted mean of per-class F1 over classes present in the TRUE
        labels ([B:2] metric of record)."""
        present = self.label_counts > 0
        f1 = self.f_measure_by_label()
        return float(f1[present].mean()) if present.any() else 0.0


class MulticlassClassificationEvaluator(Evaluator):
    """Spark-parity evaluator facade over :class:`MulticlassMetrics`.

    ``metricLabel`` selects the class for the ``...ByLabel`` metrics;
    ``logLoss`` reads ``probabilityCol`` (Spark semantics: −log of the
    true-class probability, clamped by ``eps``).  A Params stage
    (SURVEY.md §5.6), so tuning results persist the evaluator spec."""

    _METRICS = (
        "f1",
        "accuracy",
        "weightedPrecision",
        "weightedRecall",
        "weightedTruePositiveRate",
        "weightedFalsePositiveRate",
        "weightedFMeasure",
        "truePositiveRateByLabel",
        "falsePositiveRateByLabel",
        "precisionByLabel",
        "recallByLabel",
        "fMeasureByLabel",
        "logLoss",
        "hammingLoss",
        "macroF1",
    )
    _SMALLER_IS_BETTER = ("logLoss", "hammingLoss", "weightedFalsePositiveRate",
                          "falsePositiveRateByLabel")

    metricName = Param("metric to compute", default="f1",
                       validator=validators.one_of(*_METRICS))
    labelCol = Param("true-label column", default="label")
    predictionCol = Param("prediction column", default="prediction")
    probabilityCol = Param("class-probability column (logLoss)",
                           default="probability")
    metricLabel = Param("class index for the ...ByLabel metrics",
                        default=0.0, validator=validators.gteq(0))
    beta = Param("F-measure beta", default=1.0, validator=validators.gt(0))
    eps = Param("logLoss probability clamp", default=1e-15,
                validator=validators.in_range(0, 0.5))
    weightCol = Param("optional row-weight column", default=None)

    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def metrics(self, frame: Frame) -> MulticlassMetrics:
        # by-label metrics: size the confusion matrix to cover metricLabel
        # so a class absent from this frame reads as 0 (the 0/0 -> 0
        # convention) instead of an IndexError mid-tuning
        labels = frame[self.getLabelCol()]
        preds = frame[self.getPredictionCol()]
        num_classes = None
        if self.getMetricName().endswith("ByLabel"):
            # size the matrix up-front (cheap host max) so the device
            # confusion-matrix reduction runs exactly once
            observed = int(
                max(
                    np.max(labels, initial=-1.0), np.max(preds, initial=-1.0)
                )
            ) + 1
            num_classes = max(observed, int(self.getMetricLabel()) + 1)
        weight_col = self.getWeightCol()
        weights = frame[weight_col] if weight_col else None
        return MulticlassMetrics(
            labels, preds, weights=weights, num_classes=num_classes,
            mesh=self._mesh,
        )

    def _log_loss(self, frame: Frame) -> float:
        prob = np.asarray(frame[self.getProbabilityCol()], np.float64)
        y = np.asarray(frame[self.getLabelCol()]).astype(np.int64)
        p_true = prob[np.arange(len(y)), y]
        eps = self.getEps()
        # Spark clamps to [eps, 1-eps] on both sides (MulticlassMetrics.logLoss)
        losses = -np.log(np.clip(p_true, eps, 1.0 - eps))
        weight_col = self.getWeightCol()
        if weight_col:
            w = np.asarray(frame[weight_col], np.float64)
            return float(np.sum(w * losses) / np.sum(w))
        return float(np.mean(losses))

    def evaluate(self, frame: Frame) -> float:
        name = self.getMetricName()
        if name == "logLoss":
            return self._log_loss(frame)
        m = self.metrics(frame)
        lbl = int(self.getMetricLabel())
        beta = self.getBeta()
        if name == "f1":
            return m.weighted_f_measure()
        if name == "accuracy":
            return m.accuracy
        if name == "weightedPrecision":
            return m.weighted_precision()
        if name in ("weightedRecall", "weightedTruePositiveRate"):
            return m.weighted_recall()
        if name == "weightedFalsePositiveRate":
            return m.weighted_false_positive_rate()
        if name == "weightedFMeasure":
            return m.weighted_f_measure(beta)
        if name == "truePositiveRateByLabel":
            return float(m.recall_by_label()[lbl])
        if name == "falsePositiveRateByLabel":
            return float(m.false_positive_rate_by_label()[lbl])
        if name == "precisionByLabel":
            return float(m.precision_by_label()[lbl])
        if name == "recallByLabel":
            return float(m.recall_by_label()[lbl])
        if name == "fMeasureByLabel":
            return float(m.f_measure_by_label(beta)[lbl])
        if name == "hammingLoss":
            return m.hamming_loss()
        return m.macro_f1()

    def isLargerBetter(self) -> bool:
        return self.getMetricName() not in self._SMALLER_IS_BETTER
