"""BinaryClassificationEvaluator — areaUnderROC / areaUnderPR [B:7].

Behavioral spec: SURVEY.md §2.4 (upstream
``ml/evaluation/BinaryClassificationEvaluator.scala`` ->
``mllib/evaluation/BinaryClassificationMetrics.scala`` [U]): score each row
by ``rawPrediction[:, 1]``, sweep thresholds over distinct scores (ties
grouped, Spark-style), trapezoidal areas.  The ROC curve is anchored at
(0,0) and (1,1); the PR curve prepends ``(0, precision_of_first_point)``.
Host-side: the sweep is a sort + cumsum over at most N rows (SURVEY.md §2.4
"sorted-threshold sweep on host").
"""

from __future__ import annotations

import numpy as np

from sntc_tpu.core.base import Evaluator
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


def _curves(labels: np.ndarray, scores: np.ndarray, weights: np.ndarray = None):
    y = np.asarray(labels, np.float64)
    s = np.asarray(scores, np.float64)
    w = np.ones_like(y) if weights is None else np.asarray(weights, np.float64)
    order = np.argsort(-s, kind="stable")
    y, s, w = y[order], s[order], w[order]
    # group ties: cumulative counts at the end of each distinct-score run
    boundary = np.flatnonzero(np.diff(s)) if len(s) else np.array([], np.int64)
    ends = np.concatenate([boundary, [len(s) - 1]]) if len(s) else boundary
    cum_tp = np.cumsum(w * y)[ends]
    cum_fp = np.cumsum(w * (1.0 - y))[ends]
    total_p = cum_tp[-1] if len(cum_tp) else 0.0
    total_n = cum_fp[-1] if len(cum_fp) else 0.0
    return cum_tp, cum_fp, total_p, total_n


def area_under_roc(labels, scores, weights=None) -> float:
    tp, fp, p, n = _curves(labels, scores, weights)
    if p == 0 or n == 0:
        return 0.0
    tpr = np.concatenate([[0.0], tp / p, [1.0]])
    fpr = np.concatenate([[0.0], fp / n, [1.0]])
    return float(np.trapezoid(tpr, fpr))


def area_under_pr(labels, scores, weights=None) -> float:
    tp, fp, p, _ = _curves(labels, scores, weights)
    if p == 0:
        return 0.0
    recall = tp / p
    precision = tp / np.maximum(tp + fp, 1e-300)
    # Spark prepends (0, precision of the first point)
    recall = np.concatenate([[0.0], recall])
    precision = np.concatenate([[precision[0]], precision])
    return float(np.trapezoid(precision, recall))


class BinaryClassificationEvaluator(Evaluator):
    _METRICS = ("areaUnderROC", "areaUnderPR")

    metricName = Param("metric to compute", default="areaUnderROC",
                       validator=validators.one_of(*_METRICS))
    labelCol = Param("true-label column", default="label")
    rawPredictionCol = Param("margins / score column",
                             default="rawPrediction")
    weightCol = Param("optional row-weight column", default=None)

    def evaluate(self, frame: Frame) -> float:
        raw = frame[self.getRawPredictionCol()]
        scores = raw[:, 1] if raw.ndim == 2 else raw
        labels = frame[self.getLabelCol()]
        weight_col = self.getWeightCol()
        w = frame[weight_col] if weight_col else None
        fn = (
            area_under_roc
            if self.getMetricName() == "areaUnderROC"
            else area_under_pr
        )
        return fn(labels, scores, w)
