from sntc_tpu.evaluation.multiclass import (
    MulticlassClassificationEvaluator,
    MulticlassMetrics,
)
from sntc_tpu.evaluation.binary import BinaryClassificationEvaluator

__all__ = [
    "MulticlassClassificationEvaluator",
    "MulticlassMetrics",
    "BinaryClassificationEvaluator",
]
