from sntc_tpu.evaluation.multiclass import (
    MulticlassClassificationEvaluator,
    MulticlassMetrics,
)
from sntc_tpu.evaluation.binary import BinaryClassificationEvaluator
from sntc_tpu.evaluation.regression import RegressionEvaluator
from sntc_tpu.evaluation.clustering import ClusteringEvaluator
from sntc_tpu.evaluation.ranking import (
    MultilabelClassificationEvaluator,
    RankingEvaluator,
)

__all__ = [
    "RankingEvaluator",
    "MultilabelClassificationEvaluator",
    "MulticlassClassificationEvaluator",
    "MulticlassMetrics",
    "BinaryClassificationEvaluator",
    "RegressionEvaluator",
    "ClusteringEvaluator",
]
