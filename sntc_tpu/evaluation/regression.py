"""RegressionEvaluator — rmse / mse / r2 / mae / var [B:2-adjacent].

Behavioral spec: upstream ``ml/evaluation/RegressionEvaluator.scala`` ->
``mllib/evaluation/RegressionMetrics.scala`` [U]: weighted residual
moments over (prediction, label) pairs; ``r2`` uses the weighted total
sum of squares about the weighted label mean; ``var`` is Spark's
``explainedVariance`` (SS_reg/n: predictions about the weighted label
mean).  ``isLargerBetter`` is False except for ``r2``/``var``.

Host-side: five scalar reductions over two columns — no device program
is worth the dispatch (SURVEY.md §2.4's "on host" rule for tiny metric
tails).
"""

from __future__ import annotations

import numpy as np

from sntc_tpu.core.frame import Frame


class RegressionEvaluator:
    _METRICS = ("rmse", "mse", "r2", "mae", "var")

    def __init__(
        self,
        metricName: str = "rmse",
        labelCol: str = "label",
        predictionCol: str = "prediction",
        weightCol: str = None,
        throughOrigin: bool = False,
    ):
        if metricName not in self._METRICS:
            raise ValueError(
                f"unknown metricName {metricName!r}; one of {self._METRICS}"
            )
        self.metricName = metricName
        self.labelCol = labelCol
        self.predictionCol = predictionCol
        self.weightCol = weightCol
        self.throughOrigin = throughOrigin

    def evaluate(self, frame: Frame) -> float:
        y = np.asarray(frame[self.labelCol], np.float64)
        pred = np.asarray(frame[self.predictionCol], np.float64)
        w = (
            np.asarray(frame[self.weightCol], np.float64)
            if self.weightCol
            else np.ones_like(y)
        )
        wsum = w.sum()
        if wsum == 0:
            return 0.0
        resid = y - pred
        mse = float((w * resid**2).sum() / wsum)
        if self.metricName == "mse":
            return mse
        if self.metricName == "rmse":
            return float(np.sqrt(mse))
        if self.metricName == "mae":
            return float((w * np.abs(resid)).sum() / wsum)
        if self.metricName == "var":
            # explainedVariance = SS_reg / n: weighted mean squared
            # deviation of predictions about the weighted LABEL mean
            ybar = (w * y).sum() / wsum
            return float((w * (pred - ybar) ** 2).sum() / wsum)
        # r2: 1 - SS_res / SS_tot (about 0 when throughOrigin)
        ybar = 0.0 if self.throughOrigin else (w * y).sum() / wsum
        ss_tot = float((w * (y - ybar) ** 2).sum())
        ss_res = float((w * resid**2).sum())
        if ss_tot == 0:
            return 0.0
        return 1.0 - ss_res / ss_tot

    def isLargerBetter(self) -> bool:
        return self.metricName in ("r2", "var")
