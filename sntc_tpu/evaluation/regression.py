"""RegressionEvaluator — rmse / mse / r2 / mae / var [B:2-adjacent].

Behavioral spec: upstream ``ml/evaluation/RegressionEvaluator.scala`` ->
``mllib/evaluation/RegressionMetrics.scala`` [U]: weighted residual
moments over (prediction, label) pairs; ``r2`` uses the weighted total
sum of squares about the weighted label mean; ``var`` is Spark's
``explainedVariance`` (SS_reg/n: predictions about the weighted label
mean).  ``isLargerBetter`` is False except for ``r2``/``var``.

Host-side: five scalar reductions over two columns — no device program
is worth the dispatch (SURVEY.md §2.4's "on host" rule for tiny metric
tails).
"""

from __future__ import annotations

import numpy as np

from sntc_tpu.core.base import Evaluator
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


class RegressionEvaluator(Evaluator):
    _METRICS = ("rmse", "mse", "r2", "mae", "var")

    metricName = Param("metric to compute", default="rmse",
                       validator=validators.one_of(*_METRICS))
    labelCol = Param("true-label column", default="label")
    predictionCol = Param("prediction column", default="prediction")
    weightCol = Param("optional row-weight column", default=None)
    throughOrigin = Param("r2 about 0 instead of the label mean",
                          default=False, validator=validators.is_bool())

    def evaluate(self, frame: Frame) -> float:
        metric = self.getMetricName()
        y = np.asarray(frame[self.getLabelCol()], np.float64)
        pred = np.asarray(frame[self.getPredictionCol()], np.float64)
        weight_col = self.getWeightCol()
        w = (
            np.asarray(frame[weight_col], np.float64)
            if weight_col
            else np.ones_like(y)
        )
        wsum = w.sum()
        if wsum == 0:
            return 0.0
        resid = y - pred
        mse = float((w * resid**2).sum() / wsum)
        if metric == "mse":
            return mse
        if metric == "rmse":
            return float(np.sqrt(mse))
        if metric == "mae":
            return float((w * np.abs(resid)).sum() / wsum)
        if metric == "var":
            # explainedVariance = SS_reg / n: weighted mean squared
            # deviation of predictions about the weighted LABEL mean
            ybar = (w * y).sum() / wsum
            return float((w * (pred - ybar) ** 2).sum() / wsum)
        # r2: 1 - SS_res / SS_tot (about 0 when throughOrigin)
        ybar = 0.0 if self.getThroughOrigin() else (w * y).sum() / wsum
        ss_tot = float((w * (y - ybar) ** 2).sum())
        ss_res = float((w * resid**2).sum())
        if ss_tot == 0:
            return 0.0
        return 1.0 - ss_res / ss_tot

    def isLargerBetter(self) -> bool:
        return self.getMetricName() in ("r2", "var")
