"""Model tuning — ParamGridBuilder / CrossValidator / TrainValidationSplit.

Behavioral spec: SURVEY.md §2.4 (upstream ``ml/tuning/CrossValidator.scala``
[U]): k-fold × param-grid search, metric averaged over folds per grid
point, best point refit on the full data; ``TrainValidationSplit`` is the
single-split variant.

Task parallelism (SURVEY.md §2.5): Spark overlapped grid fits with a
``parallelism`` thread pool.  Here, estimators that expose
``supports_batched_grid``/``_fit_grid`` (LogisticRegression) run the WHOLE
grid as one vmapped device program per fold — data uploaded and summarized
once, every LBFGS iteration MXU-batched over the grid axis.  For
estimators without a batched path, fits run sequentially (each already
saturates the mesh) and a ``parallelism`` > 1 request logs a warning
instead of silently no-opping.  ``SNTC_TUNING_BATCH=0`` forces the
sequential path (debugging/verification).
"""

from __future__ import annotations

import logging
import os
from itertools import product
from typing import Any, Dict, List, Optional

import numpy as np

from sntc_tpu.core.base import Estimator, Model, Pipeline, PipelineModel
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.resilience import (
    RetryPolicy,
    emit_event,
    fault_point,
    with_retries,
)

logger = logging.getLogger(__name__)

# the default per-cell policy when faultTolerant=True and the caller
# didn't pass one: one in-place retry, near-immediate (a CV cell failure
# is usually deterministic — the retry catches transient device/host
# flakes, then the cell degrades to NaN)
_DEFAULT_CV_POLICY = RetryPolicy(
    max_attempts=2, base_delay_s=0.01, max_delay_s=0.5, jitter=0.0
)


def _is_batched(estimator, grid) -> bool:
    return (
        os.environ.get("SNTC_TUNING_BATCH", "1") != "0"
        and hasattr(estimator, "supports_batched_grid")
        and estimator.supports_batched_grid(grid)
    )


def _pipeline_grid_plan(estimator, grid):
    """``(prefix_stages, head_estimator)`` when ``estimator`` is a
    Pipeline whose grid params ALL target its final stage (an
    Estimator) — the plan that lets tuning fit the feature prefix ONCE
    per fold/split and sweep only the head.  None otherwise (including
    an empty grid, where there is nothing to sweep).

    Name-based grids on a Pipeline are resolved against the final
    estimator by definition; a grid key no stage can own still fails
    loudly in ``copy`` exactly as before."""
    if not isinstance(estimator, Pipeline):
        return None
    keys = set().union(*grid) if grid else set()
    if not keys:
        return None
    stages = estimator.getStages()
    if not stages or not isinstance(stages[-1], Estimator):
        return None
    head = stages[-1]
    if not all(head.hasParam(k) for k in keys):
        return None
    return list(stages[:-1]), head


def _estimator_reads(head) -> list:
    """Columns the head estimator's fit consumes: its declared input
    columns (``PipelineStage.input_columns`` — overridable by stages
    with nonstandard input params) plus label/weight, which only exist
    at fit time — so the fused prefix keeps every column the head sweep
    needs."""
    out = list(head.input_columns())
    for name in ("labelCol", "weightCol"):
        if not head.hasParam(name) or not head.isDefined(name):
            continue
        val = head.getOrDefault(name)
        if val:
            out.append(val)
    return out


def _fit_prefix_transform(prefix_stages, head, frame: Frame):
    """Fit the feature prefix on ``frame`` and transform it ONCE through
    the whole-pipeline fusion compiler (``sntc_tpu.fuse``): one device
    program per fusible run instead of a per-stage host round trip, and
    the result is reused across every grid point.  Returns
    ``(prefix PipelineModel, fused prefix or None, transformed frame)``."""
    from sntc_tpu.fuse import compile_pipeline

    if not prefix_stages:
        return PipelineModel(stages=[]), None, frame
    prefix = Pipeline(stages=list(prefix_stages)).fit(frame)
    fused = compile_pipeline(
        prefix, keep=_estimator_reads(head), fuse_heads=False
    )
    return prefix, fused, fused.transform(frame)


def _fit_with_params(estimator, frame: Frame, params, plan=None):
    """One full fit of ``estimator`` under a grid-point override map,
    honoring the pipeline-grid plan (params bind to the head stage)."""
    if plan is None:
        return estimator.copy(params).fit(frame)
    prefix_stages, head = plan
    return Pipeline(
        stages=list(prefix_stages) + [head.copy(params)]
    ).fit(frame)


def _grid_fit(estimator, train: Frame, grid):
    """Yields one fitted model per grid point, in order: one vmapped
    program when the estimator supports it, otherwise a sequential loop
    (lazy, so the caller holds at most one sequential model at a time).
    Pipeline estimators with a head-only grid fit the feature prefix
    ONCE and sweep just the head (batched when the head supports it),
    yielding full PipelineModels."""
    plan = _pipeline_grid_plan(estimator, grid)
    if plan is not None:
        prefix_stages, head = plan
        prefix, _, head_train = _fit_prefix_transform(
            prefix_stages, head, train
        )
        for model in _grid_fit(head, head_train, grid):
            yield PipelineModel(stages=prefix.getStages() + [model])
        return
    if _is_batched(estimator, grid):
        yield from estimator._fit_grid(train, grid)
        return
    for params in grid:
        yield estimator.copy(params).fit(train)


def _warn_parallelism_noop(estimator, grid, parallelism: int):
    if parallelism <= 1:
        return
    if not _is_batched(estimator, grid):
        logger.warning(
            "parallelism=%d has no effect for %s: grid fits run "
            "sequentially (each fit saturates the device mesh); "
            "estimators with a batched grid path (e.g. LogisticRegression) "
            "overlap grid points automatically",
            parallelism, type(estimator).__name__,
        )


class ParamGridBuilder:
    def __init__(self):
        self._grid: Dict[str, List[Any]] = {}

    def addGrid(self, param, values) -> "ParamGridBuilder":
        name = param if isinstance(param, str) else param.name
        self._grid[name] = list(values)
        return self

    def baseOn(self, **fixed) -> "ParamGridBuilder":
        for k, v in fixed.items():
            self._grid[k] = [v]
        return self

    def build(self) -> List[Dict[str, Any]]:
        if not self._grid:
            return [{}]
        names = list(self._grid)
        return [
            dict(zip(names, combo))
            for combo in product(*(self._grid[n] for n in names))
        ]


class _TuningParams:
    numFolds = Param("cross-validation folds", default=3, validator=validators.gteq(2))
    seed = Param("fold split seed", default=0)
    parallelism = Param(
        "accepted for API parity; batched-grid estimators overlap grid "
        "points on-device regardless, others warn and run sequentially",
        default=1,
        validator=validators.gteq(1),
    )
    collectSubModels = Param("keep every (fold, grid) sub-model", default=False,
                             validator=validators.is_bool())
    foldCol = Param(
        "optional column of user-assigned fold indices in [0, numFolds)",
        default=None,
    )
    faultTolerant = Param(
        "retry a failed (fold, grid) cell fit under the resilience "
        "policy, then record NaN for that cell and keep the grid "
        "search alive instead of aborting (forces per-cell sequential "
        "fits — fault isolation needs cell-granular execution)",
        default=False,
        validator=validators.is_bool(),
    )


class CrossValidator(_TuningParams, Estimator):
    def __init__(self, estimator=None, estimatorParamMaps=None, evaluator=None,
                 retryPolicy=None, **kwargs):
        super().__init__(**kwargs)
        if estimator is None or evaluator is None:
            raise ValueError("CrossValidator requires estimator and evaluator")
        self.estimator = estimator
        self.estimatorParamMaps = estimatorParamMaps or [{}]
        self.evaluator = evaluator
        # in-memory only (not persisted): the per-cell policy used when
        # faultTolerant=True; defaults to one quick in-place retry
        self.retryPolicy = retryPolicy

    def _fit(self, frame: Frame) -> "CrossValidatorModel":
        k = self.getNumFolds()
        if self.getFoldCol():
            raw = np.asarray(frame[self.getFoldCol()])
            fold_of = raw.astype(np.int64)
            if not np.array_equal(raw.astype(np.float64), fold_of):
                raise ValueError("foldCol values must be integers")
            if fold_of.min(initial=0) < 0 or fold_of.max(initial=0) >= k:
                raise ValueError(
                    f"foldCol values must lie in [0, numFolds={k})"
                )
            present = np.bincount(fold_of, minlength=k)
            if (present == 0).any():
                empty = np.flatnonzero(present == 0).tolist()
                raise ValueError(
                    f"foldCol leaves folds {empty} empty: every fold in "
                    f"[0, numFolds={k}) needs rows (an empty fold would be "
                    "silently fit/scored on nothing)"
                )
        else:
            rng = np.random.default_rng(self.getSeed())
            fold_of = rng.integers(0, k, size=frame.num_rows)
        grid = self.estimatorParamMaps
        metrics = np.zeros((len(grid), k))
        sub_models: Optional[List[List[Model]]] = (
            [[] for _ in grid] if self.getCollectSubModels() else None
        )

        plan = _pipeline_grid_plan(self.estimator, grid)
        # the hoisted head is what actually sweeps the grid — warn about
        # ITS batching capability, not the (never-batched) Pipeline shell
        _warn_parallelism_noop(
            self.estimator if plan is None else plan[1], grid,
            self.getParallelism(),
        )
        if self.getFaultTolerant():
            self._fit_folds_tolerant(frame, fold_of, k, grid, metrics,
                                     sub_models, plan)
        elif plan is not None:
            # Pipeline estimator, head-only grid: per fold, fit the
            # feature prefix ONCE and push train AND valid through the
            # fused prefix program once — every grid point reuses the
            # on-device-transformed features instead of re-running the
            # whole feature chain (sntc_tpu.fuse; the head sweep still
            # batches on-device when the head supports grids)
            self._fit_folds_pipeline(frame, fold_of, k, grid, metrics,
                                     sub_models, plan)
        else:
            # strongest path: the whole k-fold × grid sweep as one vmapped
            # device program (folds are per-lane weight masks; data uploads
            # once) — available when the estimator supports batched grids
            fold_models = None
            if _is_batched(self.estimator, grid) and hasattr(
                self.estimator, "_fit_grid_folds"
            ):
                fold_models = self.estimator._fit_grid_folds(
                    frame, grid, fold_of, k
                )
            for fold in range(k):
                valid = frame.filter(fold_of == fold)
                models = (
                    fold_models[fold]
                    if fold_models is not None
                    else _grid_fit(
                        self.estimator, frame.filter(fold_of != fold), grid
                    )
                )
                for gi, model in enumerate(models):
                    metrics[gi, fold] = self.evaluator.evaluate(
                        model.transform(valid)
                    )
                    if sub_models is not None:
                        sub_models[gi].append(model)

        larger = self.evaluator.isLargerBetter()
        if self.getFaultTolerant():
            # degraded cells are NaN: average each grid point over its
            # SURVIVING folds; a grid point with no surviving fold can
            # never win
            counts = (~np.isnan(metrics)).sum(axis=1)
            if not counts.any():
                raise RuntimeError(
                    "CrossValidator: every (fold, grid) cell failed "
                    "even under the fault-tolerance policy"
                )
            sums = np.nansum(metrics, axis=1)
            avg = np.where(
                counts > 0, sums / np.maximum(counts, 1),
                -np.inf if larger else np.inf,
            )
        else:
            avg = metrics.mean(axis=1)
        best_idx = int(np.argmax(avg)) if larger else int(np.argmin(avg))
        refit = lambda: _fit_with_params(
            self.estimator, frame, grid[best_idx], plan
        )
        if self.getFaultTolerant():
            # the final refit deserves the same transient-flake cover as
            # the cells — losing the whole surviving sweep to one blip
            # at the finish line would defeat the tolerance
            best_model = with_retries(
                refit, self.retryPolicy or _DEFAULT_CV_POLICY,
                site="cv.fit",
            )
        else:
            best_model = refit()
        return CrossValidatorModel(
            bestModel=best_model,
            avgMetrics=avg.tolist(),
            bestIndex=best_idx,
            subModels=sub_models,
            estimator=self.estimator,
            evaluator=self.evaluator,
            estimatorParamMaps=grid,
        )

    def _fit_folds_pipeline(self, frame, fold_of, k, grid, metrics,
                            sub_models, plan) -> None:
        """The hoisted pipeline sweep: per fold, the feature prefix is
        fit once and both splits flow through the fused prefix program
        once; grid points fit and score on the ALREADY-transformed
        frames (metrics are identical to fitting the whole pipeline per
        cell — the prefix has no grid params by construction).
        Sub-models are full PipelineModels, as the sequential path
        produces."""
        prefix_stages, head = plan
        for fold in range(k):
            prefix, fused_prefix, head_train = _fit_prefix_transform(
                prefix_stages, head, frame.filter(fold_of != fold)
            )
            head_valid = (
                fused_prefix.transform(frame.filter(fold_of == fold))
                if fused_prefix is not None
                else frame.filter(fold_of == fold)
            )
            for gi, model in enumerate(_grid_fit(head, head_train, grid)):
                metrics[gi, fold] = self.evaluator.evaluate(
                    model.transform(head_valid)
                )
                if sub_models is not None:
                    sub_models[gi].append(
                        PipelineModel(stages=prefix.getStages() + [model])
                    )

    def _fit_folds_tolerant(self, frame, fold_of, k, grid, metrics,
                            sub_models, plan=None) -> None:
        """Per-(fold, grid-point) execution under the resilience policy:
        each cell fit+evaluate retries per ``retryPolicy`` (site
        ``cv.fit``), and on exhaustion the cell records NaN with a
        structured ``cv_cell_degraded`` event — the grid search
        continues.  Cell-granular by construction: the batched vmapped
        sweep cannot isolate one lane's failure (and the pipeline-grid
        plan's prefix hoist is likewise skipped — a cell is the WHOLE
        pipeline fit, so one cell's poison cannot leak into another's
        shared features)."""
        policy = self.retryPolicy or _DEFAULT_CV_POLICY
        for fold in range(k):
            valid = frame.filter(fold_of == fold)
            train = frame.filter(fold_of != fold)
            for gi, params in enumerate(grid):
                def _cell(params=params):
                    fault_point("cv.fit")
                    model = _fit_with_params(
                        self.estimator, train, params, plan
                    )
                    return model, self.evaluator.evaluate(
                        model.transform(valid)
                    )

                try:
                    model, metric = with_retries(
                        _cell, policy, site="cv.fit"
                    )
                except Exception as e:
                    metrics[gi, fold] = np.nan
                    emit_event(
                        event="cv_cell_degraded", site="cv.fit",
                        fold=fold, grid_index=gi, error=repr(e),
                    )
                    logger.warning(
                        "CrossValidator: fold %d grid point %d failed "
                        "(%r); cell recorded as NaN", fold, gi, e,
                    )
                    if sub_models is not None:
                        sub_models[gi].append(None)
                    continue
                metrics[gi, fold] = metric
                if sub_models is not None:
                    sub_models[gi].append(model)

    # -- persistence: a saved CrossValidator round-trips its full spec
    # (estimator + evaluator stages, grid in JSON), Spark ReadWrite parity

    def _sub_stages(self):
        return [self.estimator, self.evaluator]

    def _save_extra(self):
        return {"estimatorParamMaps": self.estimatorParamMaps}, {}

    @classmethod
    def _from_sub_stages(cls, stages, params, extra=None):
        obj = cls(
            estimator=stages[0], evaluator=stages[1],
            estimatorParamMaps=(extra or {}).get("estimatorParamMaps")
            or [{}],
        )
        obj.setParams(**params)
        return obj


class CrossValidatorModel(Model):
    """Best-model wrapper; carries ``avgMetrics`` per grid point and —
    for Spark save/load parity — the tuning spec (``estimator``,
    ``evaluator``, ``estimatorParamMaps``), all of which round-trip
    through ``save``/``load`` so a loaded result can re-run the search.
    ``subModels`` are in-memory only (not persisted)."""

    def __init__(self, bestModel: Model = None, avgMetrics: List[float] = None,
                 bestIndex: int = 0, subModels=None, estimator=None,
                 evaluator=None, estimatorParamMaps=None, **kwargs):
        super().__init__(**kwargs)
        self.bestModel = bestModel
        self.avgMetrics = avgMetrics or []
        self.bestIndex = bestIndex
        self.subModels = subModels
        self.estimator = estimator
        self.evaluator = evaluator
        self.estimatorParamMaps = estimatorParamMaps or []

    def transform(self, frame: Frame) -> Frame:
        return self.bestModel.transform(frame)

    def _has_spec(self) -> bool:
        return self.estimator is not None and self.evaluator is not None

    def _sub_stages(self):
        stages = [self.bestModel]
        if self._has_spec():
            stages += [self.estimator, self.evaluator]
        return stages

    def _save_extra(self):
        return {
            "avgMetrics": self.avgMetrics,
            "bestIndex": self.bestIndex,
            "estimatorParamMaps": self.estimatorParamMaps or None,
            "has_spec": self._has_spec(),
        }, {}

    @classmethod
    def _from_sub_stages(cls, stages, params, extra=None):
        extra = extra or {}
        est = ev = None
        if extra.get("has_spec") and len(stages) >= 3:
            est, ev = stages[1], stages[2]
        obj = cls(
            bestModel=stages[0],
            avgMetrics=extra.get("avgMetrics") or [],
            bestIndex=int(extra.get("bestIndex", 0)),
            estimator=est,
            evaluator=ev,
            estimatorParamMaps=extra.get("estimatorParamMaps"),
        )
        obj.setParams(**params)
        return obj


class _TvsParams:
    trainRatio = Param("train fraction", default=0.75, validator=validators.in_range(0, 1))
    seed = Param("split seed", default=0)
    parallelism = Param(
        "accepted for API parity; batched-grid estimators overlap grid "
        "points on-device regardless, others warn and run sequentially",
        default=1, validator=validators.gteq(1),
    )
    collectSubModels = Param("keep every grid-point sub-model", default=False,
                             validator=validators.is_bool())


class TrainValidationSplit(_TvsParams, Estimator):
    def __init__(self, estimator=None, estimatorParamMaps=None, evaluator=None,
                 **kwargs):
        super().__init__(**kwargs)
        if estimator is None or evaluator is None:
            raise ValueError(
                "TrainValidationSplit requires estimator and evaluator"
            )
        self.estimator = estimator
        self.estimatorParamMaps = estimatorParamMaps or [{}]
        self.evaluator = evaluator

    def _fit(self, frame: Frame) -> "TrainValidationSplitModel":
        ratio = self.getTrainRatio()
        train, valid = frame.random_split(
            [ratio, 1 - ratio], seed=self.getSeed()
        )
        grid = self.estimatorParamMaps
        metrics = []
        sub_models: Optional[List[Model]] = (
            [] if self.getCollectSubModels() else None
        )
        plan = _pipeline_grid_plan(self.estimator, grid)
        # the hoisted head is what actually sweeps the grid — warn about
        # ITS batching capability, not the (never-batched) Pipeline shell
        _warn_parallelism_noop(
            self.estimator if plan is None else plan[1], grid,
            self.getParallelism(),
        )
        if plan is not None:
            # pipeline-grid hoist (mirrors CrossValidator): the feature
            # prefix fits once and BOTH splits flow through the fused
            # prefix program once; only the head sweeps the grid
            prefix_stages, head = plan
            prefix, fused_prefix, head_train = _fit_prefix_transform(
                prefix_stages, head, train
            )
            head_valid = (
                fused_prefix.transform(valid)
                if fused_prefix is not None
                else valid
            )
            for model in _grid_fit(head, head_train, grid):
                metrics.append(
                    self.evaluator.evaluate(model.transform(head_valid))
                )
                if sub_models is not None:
                    sub_models.append(
                        PipelineModel(stages=prefix.getStages() + [model])
                    )
        else:
            for model in _grid_fit(self.estimator, train, grid):
                metrics.append(
                    self.evaluator.evaluate(model.transform(valid))
                )
                if sub_models is not None:
                    sub_models.append(model)
        arr = np.asarray(metrics)
        best_idx = (
            int(np.argmax(arr))
            if self.evaluator.isLargerBetter()
            else int(np.argmin(arr))
        )
        best_model = _fit_with_params(
            self.estimator, frame, grid[best_idx], plan
        )
        return TrainValidationSplitModel(
            bestModel=best_model, validationMetrics=metrics,
            bestIndex=best_idx, subModels=sub_models,
            estimator=self.estimator, evaluator=self.evaluator,
            estimatorParamMaps=grid,
        )

    def _sub_stages(self):
        return [self.estimator, self.evaluator]

    def _save_extra(self):
        return {"estimatorParamMaps": self.estimatorParamMaps}, {}

    @classmethod
    def _from_sub_stages(cls, stages, params, extra=None):
        obj = cls(
            estimator=stages[0], evaluator=stages[1],
            estimatorParamMaps=(extra or {}).get("estimatorParamMaps")
            or [{}],
        )
        obj.setParams(**params)
        return obj


class TrainValidationSplitModel(Model):
    """Best-model wrapper; persistence mirrors
    :class:`CrossValidatorModel` (spec + metrics round-trip,
    ``subModels`` in-memory only)."""

    def __init__(self, bestModel: Model = None, validationMetrics=None,
                 bestIndex: int = 0, subModels=None, estimator=None,
                 evaluator=None, estimatorParamMaps=None, **kwargs):
        super().__init__(**kwargs)
        self.bestModel = bestModel
        self.validationMetrics = validationMetrics or []
        self.bestIndex = bestIndex
        self.subModels = subModels
        self.estimator = estimator
        self.evaluator = evaluator
        self.estimatorParamMaps = estimatorParamMaps or []

    def transform(self, frame: Frame) -> Frame:
        return self.bestModel.transform(frame)

    def _has_spec(self) -> bool:
        return self.estimator is not None and self.evaluator is not None

    def _sub_stages(self):
        stages = [self.bestModel]
        if self._has_spec():
            stages += [self.estimator, self.evaluator]
        return stages

    def _save_extra(self):
        return {
            "validationMetrics": self.validationMetrics,
            "bestIndex": self.bestIndex,
            "estimatorParamMaps": self.estimatorParamMaps or None,
            "has_spec": self._has_spec(),
        }, {}

    @classmethod
    def _from_sub_stages(cls, stages, params, extra=None):
        extra = extra or {}
        est = ev = None
        if extra.get("has_spec") and len(stages) >= 3:
            est, ev = stages[1], stages[2]
        obj = cls(
            bestModel=stages[0],
            validationMetrics=extra.get("validationMetrics") or [],
            bestIndex=int(extra.get("bestIndex", 0)),
            estimator=est,
            evaluator=ev,
            estimatorParamMaps=extra.get("estimatorParamMaps"),
        )
        obj.setParams(**params)
        return obj
