from sntc_tpu.tuning.cross_validator import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
    TrainValidationSplitModel,
)

__all__ = [
    "ParamGridBuilder",
    "CrossValidator",
    "CrossValidatorModel",
    "TrainValidationSplit",
    "TrainValidationSplitModel",
]
