"""Quantile binning — the ``findSplits`` analog (SURVEY.md §3.2).

Spark's tree path bins continuous features once into uint8 bin ids
(``TreePoint.convertToTreePoint`` after ``findSplits`` quantile sampling [U])
so every later pass is integer histogramming.  We keep that design because it
is exactly what the TPU wants: the 2.8M×78 dataset becomes a device-resident
uint8 tensor (~220 MB) and every histogram is a ``segment_sum`` feeding the
MXU-friendly reductions (SURVEY.md §7.1 step 4).

Edges are computed host-side on a sample (cheap, one pass) with static shape
``[F, max_bins - 1]``; duplicate edges from low-cardinality features are
harmless (empty bins).  ``bin_features`` is jitted and runs on device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def quantile_bin_edges(
    X: np.ndarray,
    max_bins: int = 32,
    sample_rows: int = 200_000,
    seed: int = 0,
) -> np.ndarray:
    """Per-feature quantile split thresholds, shape ``[F, max_bins - 1]``.

    Mirrors Spark ``findSplits``: thresholds are quantiles of a row sample.
    Features with < max_bins distinct sampled values get repeated edges
    (empty bins) instead of a ragged bin count — static shapes for XLA.
    """
    n, f = X.shape
    if n > sample_rows:
        idx = np.random.default_rng(seed).choice(n, size=sample_rows, replace=False)
        sample = X[idx]
    else:
        sample = X
    qs = np.linspace(0.0, 1.0, max_bins + 1)[1:-1]
    edges = np.quantile(sample, qs, axis=0).T.astype(np.float32)  # [F, B-1]
    return np.ascontiguousarray(edges)


@partial(jax.jit, static_argnames=())
def bin_features(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """Map ``X [N, F]`` to bin ids ``[N, F]`` (int32 in [0, B-1]) given
    ``edges [F, B-1]``: ``bin = #edges <= x`` (right-closed, Spark-style)."""

    def one_feature(col: jnp.ndarray, col_edges: jnp.ndarray) -> jnp.ndarray:
        return jnp.searchsorted(col_edges, col, side="right").astype(jnp.int32)

    return jax.vmap(one_feature, in_axes=(1, 0), out_axes=1)(X, edges)
