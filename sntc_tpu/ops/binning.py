"""Quantile binning — the ``findSplits`` analog (SURVEY.md §3.2).

Spark's tree path bins continuous features once into uint8 bin ids
(``TreePoint.convertToTreePoint`` after ``findSplits`` quantile sampling [U])
so every later pass is integer histogramming.  We keep that design because it
is exactly what the TPU wants: the 2.8M×78 dataset becomes a device-resident
uint8 tensor (~220 MB) and every histogram is a ``segment_sum`` feeding the
MXU-friendly reductions (SURVEY.md §7.1 step 4).

Edge computation is sample-based like Spark's ``findSplits`` (which draws
``max(maxBins², 10000)`` rows); measured on the bench workload, macro-F1 is
flat from 200k samples down to 10k, so the default sample scales with the
bin count.  Host (numpy) inputs compute edges on host; device-resident
columns (``jax.Array`` — e.g. handed down by a fitted scaler, or the 2.8M
full-scale matrix already in HBM) compute them ON DEVICE with a jitted
``jnp.quantile`` — no device→host round trip for the feature matrix.
``bin_features`` is jitted and runs on device.  Static output shape
``[F, max_bins - 1]``; duplicate edges from low-cardinality features are
harmless (empty bins).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _default_sample_rows(max_bins: int) -> int:
    # Spark findSplits: max(maxBins * maxBins, 10000); we add headroom
    return max(10_000, 4 * max_bins * max_bins)


@partial(jax.jit, static_argnames=("max_bins", "sample_rows"))
def _edges_device(
    X: jnp.ndarray, seed: jnp.ndarray, *, max_bins: int, sample_rows: int
) -> jnp.ndarray:
    qs = jnp.linspace(0.0, 1.0, max_bins + 1)[1:-1]
    n = X.shape[0]
    if sample_rows < n:
        # seed-keyed uniform sample without replacement, matching the
        # host path's semantics: a strided X[::k] sample would bias the
        # edges on device matrices with periodic/sorted row structure
        # (flow data ordered by time or label)
        idx = jax.random.choice(
            jax.random.PRNGKey(seed), n, shape=(sample_rows,), replace=False
        )
        sample = X[idx]
    else:
        sample = X
    return jnp.quantile(sample.astype(jnp.float32), qs, axis=0).T


def quantile_bin_edges(
    X,
    max_bins: int = 32,
    sample_rows: Optional[int] = None,
    seed: int = 0,
):
    """Per-feature quantile split thresholds, shape ``[F, max_bins - 1]``.

    Returns an ndarray matching the input's residency: numpy in → numpy
    edges (host quantile of a ``seed``-driven random row sample);
    ``jax.Array`` in → device edges from a ``seed``-keyed
    ``jax.random.choice`` row sample (without replacement) — the feature
    matrix never leaves the device.  With ``sample_rows >= n`` both paths
    use every row and agree to float tolerance (tests/test_trees.py
    parity test).
    """
    n, f = X.shape
    if sample_rows is None:
        sample_rows = _default_sample_rows(max_bins)
    if isinstance(X, jax.Array):
        return _edges_device(
            X, jnp.uint32(seed & 0xFFFFFFFF),
            max_bins=max_bins, sample_rows=min(int(sample_rows), n),
        )
    if n > sample_rows:
        idx = np.random.default_rng(seed).choice(n, size=sample_rows, replace=False)
        sample = X[idx]
    else:
        sample = X
    qs = np.linspace(0.0, 1.0, max_bins + 1)[1:-1]
    edges = np.quantile(sample, qs, axis=0).T.astype(np.float32)  # [F, B-1]
    return np.ascontiguousarray(edges)


@partial(jax.jit, static_argnames=())
def bin_features(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """Map ``X [N, F]`` to bin ids ``[N, F]`` (int32 in [0, B-1]) given
    ``edges [F, B-1]``: ``bin = #edges <= x`` (right-closed, Spark-style)."""

    def one_feature(col: jnp.ndarray, col_edges: jnp.ndarray) -> jnp.ndarray:
        return jnp.searchsorted(col_edges, col, side="right").astype(jnp.int32)

    return jax.vmap(one_feature, in_axes=(1, 0), out_axes=1)(X, edges)
