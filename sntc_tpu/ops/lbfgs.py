"""LBFGS / OWLQN minimizer, fully jitted — the Breeze optimizer analog.

Behavioral spec: SURVEY.md §2.3/§3.1: Spark drives every LR/MLP fit through
Breeze ``LBFGS`` (L2/none) or ``OWLQN`` (elastic-net L1) on the driver, with
one ``treeAggregate`` gradient pass per iteration.  Here the ENTIRE
optimization loop lives in one XLA program (``lax.while_loop``): the
value-and-grad closure reads mesh-sharded data, XLA inserts the ICI
all-reduce for the gradient sum, and no scalar ever returns to the host
until convergence — the per-iteration broadcast/reduce/driver-update round
trip of SURVEY.md §3.1 collapses into on-device compute.

Numerics: f32 (SURVEY.md §7.2 item 2 — v5e-native; the sklearn parity suite
bounds the difference).  OWLQN follows Andrew & Gao 2007: pseudo-gradient,
orthant-projected direction and line-search steps, with a per-coordinate
``l1`` weight vector so intercepts go unpenalized.

Implementation notes: circular history buffers with masked two-loop
recursion (static ``history_size``); Armijo backtracking line search as an
inner ``while_loop``; curvature-guarded history updates.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class LbfgsResult(NamedTuple):
    x: jnp.ndarray
    loss: jnp.ndarray  # final objective (incl. l1 term)
    n_iters: jnp.ndarray  # iterations actually taken
    history: jnp.ndarray  # [max_iter + 1] objective per iteration (padded with last)
    converged: jnp.ndarray


def _dot(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.dot(a, b, precision=jax.lax.Precision.HIGHEST)


def _pseudo_gradient(x, g, l1):
    """OWLQN pseudo-gradient of f(x) + sum(l1 * |x|)."""
    gp = g + l1 * jnp.sign(x)
    right = g + l1
    left = g - l1
    at_zero = jnp.where(right < 0, right, jnp.where(left > 0, left, 0.0))
    return jnp.where(x != 0, gp, at_zero)


def minimize_lbfgs(
    value_and_grad: Callable[[jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
    x0: jnp.ndarray,
    *,
    max_iter: int = 100,
    tol: float = 1e-6,
    history_size: int = 10,
    l1: Optional[jnp.ndarray] = None,
    max_linesearch: int = 30,
    c1: float = 1e-4,
    init_state=None,
    return_state: bool = False,
    iter_limit=None,
    bounds: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
):
    """Minimize ``f(x) + sum(l1 * |x|)`` where ``value_and_grad`` gives the
    smooth part.  ``l1=None`` (or all-zero) is plain LBFGS; otherwise OWLQN.

    ``bounds=(lb, ub)`` (±inf entries allowed, exclusive with ``l1``)
    switches to projected LBFGS — the Breeze ``LBFGSB`` analog behind
    Spark's bound-constrained LR: coordinates at an active bound with an
    outward-pushing gradient are frozen out of the two-loop direction, and
    every line-search candidate is clipped into the box.

    Jit-safe: call inside jit with sharded data closed over in
    ``value_and_grad``.

    Resumable (SURVEY.md §5.4 mid-fit checkpointing): pass
    ``return_state=True`` to also get the full optimizer state (a pytree of
    arrays — position, gradient, curvature memory, iteration counter,
    objective history); persist it and pass back as ``init_state`` to
    continue EXACTLY where the run stopped — the resumed trajectory is
    bit-identical to an uninterrupted one on the same hardware.  ``k`` in
    the state is the absolute iteration count; the loop runs while
    ``k < max_iter``.
    """
    d = x0.shape[0]
    m = history_size
    use_l1 = l1 is not None
    use_bounds = bounds is not None
    if use_l1 and use_bounds:
        raise ValueError("l1 and bounds are mutually exclusive (Spark parity)")
    l1v = jnp.zeros((d,), x0.dtype) if l1 is None else jnp.asarray(l1, x0.dtype)
    if use_bounds:
        lb = jnp.asarray(bounds[0], x0.dtype)
        ub = jnp.asarray(bounds[1], x0.dtype)
        x0 = jnp.clip(x0, lb, ub)

    def free_mask(x, g):
        """Coordinates free to move: not pinned at a bound the (negative)
        gradient would push them through."""
        at_lo = (x <= lb) & (g > 0)
        at_hi = (x >= ub) & (g < 0)
        return ~(at_lo | at_hi)

    def full_obj(x, f_smooth):
        if use_l1:
            return f_smooth + jnp.sum(l1v * jnp.abs(x))
        return f_smooth

    def effective_grad(x, g):
        """Gradient driving the two-loop: pseudo-gradient under L1,
        projected gradient under bounds."""
        if use_l1:
            return _pseudo_gradient(x, g, l1v)
        if use_bounds:
            return jnp.where(free_mask(x, g), g, 0.0)
        return g

    def project_orthant(x_new, xi):
        if use_l1:
            keep = jnp.sign(x_new) == xi
            # unpenalized coords (l1 == 0) are never clipped
            return jnp.where((l1v == 0) | keep, x_new, 0.0)
        return x_new

    if init_state is not None:
        state0 = dict(init_state)
        # the stored history may be shorter/longer than this run's horizon
        old_hist = state0["history"]
        hist = jnp.full((max_iter + 1,), state0["obj"], x0.dtype)
        n_copy = min(old_hist.shape[0], max_iter + 1)
        state0["history"] = hist.at[:n_copy].set(old_hist[:n_copy])
        state0["done"] = jnp.asarray(False)  # a resume request re-arms the loop
    else:
        f0, g0 = value_and_grad(x0)
        obj0 = full_obj(x0, f0)
        history0 = jnp.full((max_iter + 1,), obj0, x0.dtype)
        state0 = {
            "x": x0,
            "f": f0,  # smooth part
            "obj": obj0,  # smooth + l1
            "g": g0,  # smooth gradient
            "s_hist": jnp.zeros((m, d), x0.dtype),
            "y_hist": jnp.zeros((m, d), x0.dtype),
            "rho": jnp.zeros((m,), x0.dtype),
            "k": jnp.asarray(0, jnp.int32),
            "n_upd": jnp.asarray(0, jnp.int32),
            "done": jnp.asarray(False),
            "history": history0,
        }

    def two_loop(state, pg):
        """Standard masked two-loop recursion over the circular history."""
        n_upd, s_hist, y_hist, rho = (
            state["n_upd"], state["s_hist"], state["y_hist"], state["rho"],
        )
        q = pg
        idxs = (n_upd - 1 - jnp.arange(m)) % m  # newest -> oldest
        valid = jnp.arange(m) < jnp.minimum(n_upd, m)

        def fwd(i, carry):
            q, alphas = carry
            j = idxs[i]
            a = jnp.where(valid[i], rho[j] * _dot(s_hist[j], q), 0.0)
            q = q - a * y_hist[j]
            return q, alphas.at[i].set(a)

        q, alphas = jax.lax.fori_loop(0, m, fwd, (q, jnp.zeros((m,), x0.dtype)))

        newest = (n_upd - 1) % m
        sy = _dot(s_hist[newest], y_hist[newest])
        yy = _dot(y_hist[newest], y_hist[newest])
        gamma = jnp.where((n_upd > 0) & (yy > 0), sy / yy, 1.0)
        q = gamma * q

        def bwd(i, q):
            ii = m - 1 - i  # oldest -> newest
            j = idxs[ii]
            b = jnp.where(valid[ii], rho[j] * _dot(y_hist[j], q), 0.0)
            return q + s_hist[j] * (alphas[ii] - b)

        q = jax.lax.fori_loop(0, m, bwd, q)
        return -q  # descent direction

    def line_search(state, direction, pg):
        """Armijo backtracking; under L1, steps are orthant-projected and the
        sufficient-decrease test uses the actual displacement (OWLQN).

        The candidate's GRADIENT is computed alongside its value and
        carried out, so the outer step needs no second ``value_and_grad``
        at the accepted point — one fused forward+backward per candidate
        instead of forward-per-candidate plus forward+backward-per-step.
        Cost trade-off, with backward ≈ 2× forward: an iteration with k
        rejected candidates pays 3(k+1) units vs (k+1)+3 before —
        ~25% cheaper at the typical immediate accept (k=0, the common
        LBFGS case with α=1 on these smooth standardized objectives;
        measured 40.9 s → 31.4 s on the flagship MLP fit), break-even at
        k≈0.5, and MORE expensive in a backtrack-heavy regime.  The
        accepted-point math is unchanged either way."""
        x, obj = state["x"], state["obj"]
        xi = jnp.where(x != 0, jnp.sign(x), jnp.sign(-pg))
        gd = _dot(pg, direction)
        # first iteration: conservative step (Breeze convention)
        alpha0 = jnp.where(
            state["n_upd"] > 0,
            1.0,
            jnp.minimum(1.0, 1.0 / jnp.maximum(jnp.sum(jnp.abs(pg)), 1e-12)),
        ).astype(x0.dtype)

        def ls_cond(carry):
            it, alpha, ok, *_ = carry
            return (~ok) & (it < max_linesearch)

        def ls_body(carry):
            it, alpha, ok, x_new, f_new, obj_new, g_new = carry
            x_cand = project_orthant(x + alpha * direction, xi)
            if use_bounds:
                x_cand = jnp.clip(x_cand, lb, ub)
            f_cand, g_cand = value_and_grad(x_cand)
            obj_cand = full_obj(x_cand, f_cand)
            if use_l1 or use_bounds:
                # sufficient decrease on the ACTUAL (projected) displacement
                decrease = c1 * _dot(pg, x_cand - x)
            else:
                decrease = c1 * alpha * gd
            good = obj_cand <= obj + decrease
            return (
                it + 1,
                jnp.where(good, alpha, alpha * 0.5),
                good,
                jnp.where(good, x_cand, x_new),
                jnp.where(good, f_cand, f_new),
                jnp.where(good, obj_cand, obj_new),
                jnp.where(good, g_cand, g_new),
            )

        init = (
            jnp.asarray(0, jnp.int32), alpha0, jnp.asarray(False),
            x, state["f"], obj, state["g"],
        )
        _, _, ok, x_new, f_new, obj_new, g_new = jax.lax.while_loop(
            ls_cond, ls_body, init
        )
        return ok, x_new, f_new, obj_new, g_new

    # iter_limit: dynamic stop bound for segmented (checkpointed) runs —
    # the same compiled program serves every segment; max_iter (static)
    # only sizes the history buffer
    limit = (
        jnp.asarray(max_iter, jnp.int32)
        if iter_limit is None
        else jnp.minimum(jnp.asarray(iter_limit, jnp.int32), max_iter)
    )

    def cond(state):
        return (~state["done"]) & (state["k"] < limit)

    def body(state):
        pg = effective_grad(state["x"], state["g"])
        direction = two_loop(state, pg)
        if use_l1:
            # constrain direction to the descent orthant (Andrew & Gao eq. 4)
            direction = jnp.where(direction * pg < 0, direction, 0.0)
        if use_bounds:
            # frozen coordinates stay put; the rest clip in the line search
            direction = jnp.where(
                free_mask(state["x"], state["g"]), direction, 0.0
            )
        ok, x_new, f_new, obj_new, g_new = line_search(
            state, direction, pg
        )
        s = x_new - state["x"]
        # curvature pairs always use the SMOOTH gradient difference
        yv = g_new - state["g"]
        sy = _dot(s, yv)
        slot = state["n_upd"] % m
        good_pair = sy > 1e-10

        s_hist = jnp.where(
            good_pair, state["s_hist"].at[slot].set(s), state["s_hist"]
        )
        y_hist = jnp.where(
            good_pair, state["y_hist"].at[slot].set(yv), state["y_hist"]
        )
        rho = jnp.where(
            good_pair,
            state["rho"].at[slot].set(1.0 / jnp.where(good_pair, sy, 1.0)),
            state["rho"],
        )
        n_upd = state["n_upd"] + jnp.where(good_pair, 1, 0)

        k = state["k"] + 1
        rel_impr = jnp.abs(obj_new - state["obj"]) / jnp.maximum(
            jnp.maximum(jnp.abs(obj_new), jnp.abs(state["obj"])), 1e-12
        )
        converged = ok & (rel_impr < tol)
        stalled = ~ok
        return {
            "x": jnp.where(ok, x_new, state["x"]),
            "f": jnp.where(ok, f_new, state["f"]),
            "obj": jnp.where(ok, obj_new, state["obj"]),
            "g": jnp.where(ok, g_new, state["g"]),
            "s_hist": s_hist,
            "y_hist": y_hist,
            "rho": rho,
            "k": k,
            "n_upd": n_upd,
            "done": converged | stalled,
            "history": state["history"].at[k].set(
                jnp.where(ok, obj_new, state["obj"])
            ),
        }

    final = jax.lax.while_loop(cond, body, state0)
    # pad history beyond n_iters with the final objective
    idx = jnp.arange(max_iter + 1)
    hist = jnp.where(idx <= final["k"], final["history"], final["obj"])
    result = LbfgsResult(
        x=final["x"],
        loss=final["obj"],
        n_iters=final["k"],
        history=hist,
        converged=final["done"],
    )
    if return_state:
        return result, final
    return result
