"""Binned contingency/histogram kernels — the ``DTStatsAggregator`` analog.

Spark accumulates per-partition (feature, bin, class) sufficient statistics in
mutable JVM arrays and shuffles them to the driver (SURVEY.md §3.2).  Here the
whole statistic is one dense ``segment_sum`` per shard, ``psum``-reduced over
the mesh by the caller (sntc_tpu.parallel.collectives) — no shuffle, no
driver hop.  The same kernel serves ChiSqSelector (contingency [B:9]) and the
tree growers (per-node histograms, sntc_tpu/models/tree).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("n_bins", "n_classes"))
def binned_contingency(
    binned: jnp.ndarray,  # [N, F] int32 bin ids
    y: jnp.ndarray,  # [N] int32 class ids
    w: jnp.ndarray,  # [N] f32 row weights (0 on padding)
    *,
    n_bins: int,
    n_classes: int,
) -> jnp.ndarray:
    """Weighted (feature, bin, class) counts, shape ``[F, B, C]`` f32."""
    n, f = binned.shape
    feat_ids = jnp.arange(f, dtype=jnp.int32)[None, :]
    flat_ids = (feat_ids * n_bins + binned) * n_classes + y[:, None]
    weights = jnp.broadcast_to(w[:, None], (n, f))
    out = jax.ops.segment_sum(
        weights.ravel(),
        flat_ids.ravel(),
        num_segments=f * n_bins * n_classes,
    )
    return out.reshape(f, n_bins, n_classes)


def binned_contingency_onehot(
    binned: jnp.ndarray,  # [N, F] int32 bin ids
    y: jnp.ndarray,  # [N] int32 class ids
    w: jnp.ndarray,  # [N] f32 row weights (0 on padding)
    *,
    n_bins: int,
    n_classes: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """MXU path for :func:`binned_contingency` — the pallas level-histogram
    kernel with a single "node" (profiled on a real v5e chip: the
    segment_sum form scatter-adds 200k×78 elements and takes ~59 s; this
    one-hot contraction takes well under a second)."""
    from sntc_tpu.ops.pallas_histogram import level_histogram_pallas

    yoh = jax.nn.one_hot(y, n_classes, dtype=jnp.float32) * w[:, None]
    node0 = jnp.zeros(y.shape[0], jnp.int32)
    return level_histogram_pallas(
        binned.T, node0, yoh,
        n_nodes=1, n_bins=n_bins, interpret=interpret,
    )  # [F, B, C]


def chi_square(observed: np.ndarray) -> tuple:
    """Pearson χ² per feature from contingency ``[F, B, C]``.

    Returns ``(stats [F], p_values [F], dof [F])``.  Semantics follow Spark's
    ``ChiSqTest`` on categorical data (SURVEY.md §2.2): expected counts from
    row/column marginals, dof = (#nonempty bins - 1) * (#nonempty classes - 1).
    Host-side — the contingency is tiny (78×32×15).
    """
    from scipy.stats import chi2 as chi2_dist

    observed = np.asarray(observed, dtype=np.float64)
    f = observed.shape[0]
    stats = np.zeros(f)
    dofs = np.zeros(f, dtype=np.int64)
    for j in range(f):
        table = observed[j]
        table = table[table.sum(axis=1) > 0][:, table.sum(axis=0) > 0]
        if table.size == 0 or 1 in table.shape:
            stats[j], dofs[j] = 0.0, 0
            continue
        total = table.sum()
        expected = np.outer(table.sum(axis=1), table.sum(axis=0)) / total
        stats[j] = ((table - expected) ** 2 / expected).sum()
        dofs[j] = (table.shape[0] - 1) * (table.shape[1] - 1)
    p_values = np.where(dofs > 0, chi2_dist.sf(stats, np.maximum(dofs, 1)), 1.0)
    return stats, p_values, dofs
