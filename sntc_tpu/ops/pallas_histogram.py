"""Pallas TPU kernel: per-level tree histogram as MXU one-hot matmuls.

The tree grower's hot op (SURVEY.md §3.2/§7.2 item 1) is the
(node, feature, bin, stat) sufficient-statistics accumulation.  The XLA
fallback (sntc_tpu/ops/histogram.py + grower) lowers it to scatter-adds,
which serialize on TPU.  This kernel recasts it as dense matmuls:

    for each (feature f, row-block r):
        ids     = node_idx * B + bin[f]                  # [TILE_N]
        onehot  = (iota_cols == ids)                     # [TILE_N, NBpad]
        acc[f] += stats_blockᵀ @ onehot                  # [S, NBpad] on MXU

so the accumulation rides the systolic array instead of scatter units.
The row-block axis is the innermost grid dimension; the output block for
feature ``f`` is revisited across row-blocks and accumulated in place
(initialized at r == 0) — the standard Pallas reduction pattern.

Layouts: ``binned`` arrives transposed ``[F, N]`` so each (f, r) block is
lane-contiguous; the output is ``[F, S_pad, NB_pad]`` with the large
node×bin axis last (128-lane aligned).  Stats arrive pre-weighted
(bagging × user weight × active mask), so padded/dead rows contribute 0.

Selection: ``grower`` uses this kernel on TPU when ``SNTC_TREE_HIST=pallas``
(default remains the XLA segment-sum until the kernel is profiled on real
hardware); interpret mode backs the CPU tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU too (interpret mode); guard anyway
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


_F_BLOCK = 8  # features per grid step (TPU sublane granularity)
_ONEHOT_BUDGET = 4 * 1024 * 1024  # VMEM budget for the in-kernel one-hot
_MIN_TILE = 128


def hist_fits_pallas(n_nodes: int, n_bins: int) -> bool:
    """True if a level histogram of this width fits the kernel's VMEM
    budget at the minimum row tile (beyond it, the one-hot block alone
    would exhaust VMEM — callers fall back to the segment_sum impl)."""
    nb_pad = _round_up(max(n_nodes * n_bins + 1, 128), 128)
    return _MIN_TILE * nb_pad * 4 <= _ONEHOT_BUDGET


def _resolve_tree_hist(n_nodes_max: int, n_bins: int, mesh=None) -> str:
    """The historical ``SNTC_TREE_HIST`` selection semantics, verbatim
    (r21 moved the dispatch behind the kernel registry; this resolver
    keeps the fit-side behavior byte-identical)."""
    import os

    import jax

    on_tpu = jax.default_backend() == "tpu"
    impl = os.environ.get(
        "SNTC_TREE_HIST", "pallas" if on_tpu else "segment"
    )
    if impl == "pallas" and (
        mesh is None or not hist_fits_pallas(n_nodes_max, n_bins)
    ):
        return "segment"
    return impl


def resolve_hist_impl(n_nodes_max: int, n_bins: int, mesh=None) -> str:
    """Histogram impl selection shared by the tree grower and
    ChiSqSelector: the one-hot MXU kernel on TPU (scatter-adds serialize
    there; profiled 2.75–15× faster on a real v5e chip), segment_sum
    elsewhere, when no mesh is available, or when the widest level
    overflows the kernel's VMEM budget.  ``SNTC_TREE_HIST`` overrides.

    Since r21 the call routes through the shared kernel registry
    (``sntc_tpu.kernels.registry``) so the fit-side kernel shares the
    serve tier's fit-guard/fallback/cost accounting; the selection
    itself is unchanged (``_resolve_tree_hist``)."""
    from sntc_tpu.kernels.registry import resolve_impl

    return resolve_impl(
        "tree_hist", n_nodes_max=n_nodes_max, n_bins=n_bins, mesh=mesh
    )


def _hist_kernel(
    binned_ref, node_ref, stats_ref, acc_ref, *, n_bins, nb_pad, f_block
):
    r = pl.program_id(1)
    nodes = node_ref[0, :]  # [TILE_N] int32 (-1 = inactive)
    stats_t = stats_ref[:].T  # [S_pad, TILE_N]
    alive = nodes >= 0
    base = nodes * n_bins
    for j in range(f_block):  # unrolled: f_block matmuls per grid step
        bins = binned_ref[j, :]  # [TILE_N] int32 (feature f+j's bins)
        ids = jnp.where(alive, base + bins, nb_pad - 1)
        # dead rows point at the last padded column, which is sliced off;
        # their stats are also zero (pre-masked), so this is belt & braces
        onehot = (
            jax.lax.broadcasted_iota(jnp.int32, (bins.shape[0], nb_pad), 1)
            == ids[:, None]
        ).astype(jnp.float32)
        contrib = jnp.dot(
            stats_t, onehot, preferred_element_type=jnp.float32
        )  # [S_pad, NB_pad]

        @pl.when(r == 0)
        def _init(j=j, contrib=contrib):
            acc_ref[j] = contrib

        @pl.when(r != 0)
        def _acc(j=j, contrib=contrib):
            acc_ref[j] += contrib


@functools.partial(
    jax.jit,
    static_argnames=("n_nodes", "n_bins", "tile_n", "interpret"),
)
def level_histogram_pallas(
    binned_t: jnp.ndarray,  # [F, N] int32 (transposed bins)
    node_idx: jnp.ndarray,  # [N] int32
    weighted_stats: jnp.ndarray,  # [N, S] f32, pre-weighted/masked
    *,
    n_nodes: int,
    n_bins: int,
    tile_n: int = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """One tree's level histogram ``[n_nodes * n_bins, S]`` (LOCAL rows —
    caller psums across shards).

    Grid is ``(F/8, N/tile)``: feature blocks of 8 satisfy the TPU sublane
    tiling rule (a block's second-to-last dim must be a multiple of 8), and
    the row tile adapts so the in-VMEM one-hot ``[tile, NB_pad]`` stays
    ~4 MB regardless of the node×bin width (GBT's 128-bin levels would
    otherwise blow VMEM).
    """
    f, n = binned_t.shape
    s = weighted_stats.shape[1]
    nb = n_nodes * n_bins
    nb_pad = _round_up(max(nb + 1, 128), 128)  # +1: dead-row dump column
    s_pad = _round_up(s, 8)
    if tile_n is None:
        budget = _ONEHOT_BUDGET // (nb_pad * 4)
        tile_n = max(_MIN_TILE, min(2048, (budget // 128) * 128))
    n_pad = _round_up(n, tile_n)
    f_pad = _round_up(f, _F_BLOCK)

    if n_pad != n:
        binned_t = jnp.pad(binned_t, ((0, 0), (0, n_pad - n)))
        node_idx = jnp.pad(
            node_idx, (0, n_pad - n), constant_values=-1
        )
        weighted_stats = jnp.pad(
            weighted_stats, ((0, n_pad - n), (0, 0))
        )
    if f_pad != f:
        binned_t = jnp.pad(binned_t, ((0, f_pad - f), (0, 0)))
    if s_pad != s:
        weighted_stats = jnp.pad(weighted_stats, ((0, 0), (0, s_pad - s)))

    node_2d = node_idx[None, :]  # [1, N]
    grid = (f_pad // _F_BLOCK, n_pad // tile_n)

    out = pl.pallas_call(
        functools.partial(
            _hist_kernel, n_bins=n_bins, nb_pad=nb_pad, f_block=_F_BLOCK
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_F_BLOCK, tile_n), lambda i, r: (i, r)),  # binned_t
            pl.BlockSpec((1, tile_n), lambda i, r: (0, r)),  # node_idx
            pl.BlockSpec((tile_n, s_pad), lambda i, r: (r, 0)),  # stats
        ],
        out_specs=pl.BlockSpec(
            (_F_BLOCK, s_pad, nb_pad), lambda i, r: (i, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((f_pad, s_pad, nb_pad), jnp.float32),
        interpret=interpret,
    )(binned_t, node_2d, weighted_stats)

    # [F_pad, S_pad, NB_pad] -> [F, NB, S] (the grower's layout)
    return out[:f, :s, :nb].transpose(0, 2, 1)


# registered behind the shared kernel capability registry (r21):
# selection stays the historical SNTC_TREE_HIST resolver above, but the
# fit-side kernel now shares the serve tier's registry ⇔ docs ⇔ tests
# drift check and the sntc_kernel_* accounting
from sntc_tpu.kernels.registry import KernelSpec, register_kernel  # noqa: E402

register_kernel(
    KernelSpec(
        name="tree_hist",
        module="sntc_tpu/ops/pallas_histogram.py",
        guard_name="hist_fits_pallas",
        guard=hist_fits_pallas,
        tolerance="<=1e-5 rel f32 (pre-weighted stats accumulation)",
        fallback="XLA segment_sum level histogram (ops/histogram.py)",
        env="SNTC_TREE_HIST",
        resolver=_resolve_tree_hist,
    )
)
