"""Pallas TPU kernel: per-level tree histogram as MXU one-hot matmuls.

The tree grower's hot op (SURVEY.md §3.2/§7.2 item 1) is the
(node, feature, bin, stat) sufficient-statistics accumulation.  The XLA
fallback (sntc_tpu/ops/histogram.py + grower) lowers it to scatter-adds,
which serialize on TPU.  This kernel recasts it as dense matmuls:

    for each (feature f, row-block r):
        ids     = node_idx * B + bin[f]                  # [TILE_N]
        onehot  = (iota_cols == ids)                     # [TILE_N, NBpad]
        acc[f] += stats_blockᵀ @ onehot                  # [S, NBpad] on MXU

so the accumulation rides the systolic array instead of scatter units.
The row-block axis is the innermost grid dimension; the output block for
feature ``f`` is revisited across row-blocks and accumulated in place
(initialized at r == 0) — the standard Pallas reduction pattern.

Layouts: ``binned`` arrives transposed ``[F, N]`` so each (f, r) block is
lane-contiguous; the output is ``[F, S_pad, NB_pad]`` with the large
node×bin axis last (128-lane aligned).  Stats arrive pre-weighted
(bagging × user weight × active mask), so padded/dead rows contribute 0.

Selection: ``grower`` uses this kernel on TPU when ``SNTC_TREE_HIST=pallas``
(default remains the XLA segment-sum until the kernel is profiled on real
hardware); interpret mode backs the CPU tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU too (interpret mode); guard anyway
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _hist_kernel(binned_ref, node_ref, stats_ref, acc_ref, *, n_bins, nb_pad):
    r = pl.program_id(1)
    bins = binned_ref[0, :]  # [TILE_N] int32 (feature f's bins)
    nodes = node_ref[0, :]  # [TILE_N] int32 (-1 = inactive)
    ids = jnp.where(nodes >= 0, nodes * n_bins + bins, nb_pad - 1)
    # dead rows point at the last padded column, which is sliced off;
    # their stats are also zero (pre-masked), so this is belt & braces
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, (bins.shape[0], nb_pad), 1)
        == ids[:, None]
    ).astype(jnp.float32)
    contrib = jnp.dot(
        stats_ref[:].T, onehot, preferred_element_type=jnp.float32
    )  # [S_pad, NB_pad]

    @pl.when(r == 0)
    def _init():
        acc_ref[0] = contrib

    @pl.when(r != 0)
    def _acc():
        acc_ref[0] += contrib


@functools.partial(
    jax.jit,
    static_argnames=("n_nodes", "n_bins", "tile_n", "interpret"),
)
def level_histogram_pallas(
    binned_t: jnp.ndarray,  # [F, N] int32 (transposed bins)
    node_idx: jnp.ndarray,  # [N] int32
    weighted_stats: jnp.ndarray,  # [N, S] f32, pre-weighted/masked
    *,
    n_nodes: int,
    n_bins: int,
    tile_n: int = 1024,
    interpret: bool = False,
) -> jnp.ndarray:
    """One tree's level histogram ``[n_nodes * n_bins, S]`` (LOCAL rows —
    caller psums across shards)."""
    f, n = binned_t.shape
    s = weighted_stats.shape[1]
    nb = n_nodes * n_bins
    nb_pad = _round_up(max(nb + 1, 128), 128)  # +1: dead-row dump column
    s_pad = _round_up(s, 8)
    n_pad = _round_up(n, tile_n)

    if n_pad != n:
        binned_t = jnp.pad(binned_t, ((0, 0), (0, n_pad - n)))
        node_idx = jnp.pad(
            node_idx, (0, n_pad - n), constant_values=-1
        )
        weighted_stats = jnp.pad(
            weighted_stats, ((0, n_pad - n), (0, 0))
        )
    if s_pad != s:
        weighted_stats = jnp.pad(weighted_stats, ((0, 0), (0, s_pad - s)))

    node_2d = node_idx[None, :]  # [1, N]
    grid = (f, n_pad // tile_n)

    out = pl.pallas_call(
        functools.partial(_hist_kernel, n_bins=n_bins, nb_pad=nb_pad),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_n), lambda i, r: (i, r)),  # binned_t
            pl.BlockSpec((1, tile_n), lambda i, r: (0, r)),  # node_idx
            pl.BlockSpec((tile_n, s_pad), lambda i, r: (r, 0)),  # stats
        ],
        out_specs=pl.BlockSpec((1, s_pad, nb_pad), lambda i, r: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((f, s_pad, nb_pad), jnp.float32),
        interpret=interpret,
    )(binned_t, node_2d, weighted_stats)

    # [F, S_pad, NB_pad] -> [F, NB, S] (the grower's layout)
    return out[:, :s, :nb].transpose(0, 2, 1)
