from sntc_tpu.ops.binning import bin_features, quantile_bin_edges
from sntc_tpu.ops.histogram import binned_contingency, chi_square

__all__ = [
    "quantile_bin_edges",
    "bin_features",
    "binned_contingency",
    "chi_square",
]
