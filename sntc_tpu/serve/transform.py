"""BatchPredictor — the pandas_udf-style Arrow inference bridge [B:5].

Behavioral spec: SURVEY.md §2.6/§3.4: Spark serves ``model.transform`` row
batches through the executor→Python-worker Arrow socket protocol
(``ArrowPythonRunner``).  Here the bridge is direct: Arrow RecordBatch →
numpy → jitted predict (the model's device compute) → Arrow, chunked to
bound device memory.  No sockets, no serialization boundary — the
"pandas_udf-shaped bridge" of SURVEY.md §5.8 collapsed to a function call.
"""

from __future__ import annotations

from typing import Iterator, Union

import pyarrow as pa

from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame


class BatchPredictor:
    """Wrap a fitted model/pipeline for Arrow-batch inference."""

    def __init__(self, model: Transformer, chunk_rows: int = 131_072):
        self.model = model
        self.chunk_rows = int(chunk_rows)

    def predict_frame(self, frame: Frame) -> Frame:
        if frame.num_rows <= self.chunk_rows:
            return self.model.transform(frame)
        parts = [
            self.model.transform(frame.slice(s, min(s + self.chunk_rows, frame.num_rows)))
            for s in range(0, frame.num_rows, self.chunk_rows)
        ]
        return Frame.concat_all(parts)

    def predict_frame_async(self, frame: Frame):
        """Dispatch without blocking; returns a zero-arg finalize producing
        the output Frame (see Transformer.transform_async).  Oversized
        frames fall back to the chunked synchronous path."""
        if frame.num_rows <= self.chunk_rows:
            return self.model.transform_async(frame)
        out = self.predict_frame(frame)
        return lambda: out

    def predict_batch(
        self, batch: Union[pa.RecordBatch, pa.Table]
    ) -> pa.Table:
        return self.predict_frame(Frame.from_arrow(batch)).to_arrow()

    def predict_batches(
        self, batches: Iterator[pa.RecordBatch]
    ) -> Iterator[pa.Table]:
        for batch in batches:
            yield self.predict_batch(batch)

    __call__ = predict_frame
