"""BatchPredictor — the pandas_udf-style Arrow inference bridge [B:5].

Behavioral spec: SURVEY.md §2.6/§3.4: Spark serves ``model.transform`` row
batches through the executor→Python-worker Arrow socket protocol
(``ArrowPythonRunner``).  Here the bridge is direct: Arrow RecordBatch →
numpy → jitted predict (the model's device compute) → Arrow, chunked to
bound device memory.  No sockets, no serialization boundary — the
"pandas_udf-shaped bridge" of SURVEY.md §5.8 collapsed to a function call.

**Shape buckets** (``bucket_rows > 0``): every distinct micro-batch row
count is a fresh XLA compile of the jitted predict program — a streaming
source that delivers 1017, 1018, 1016 rows per tick recompiles forever.
Bucketing pads each batch up to the next power-of-two row count (no lower
than ``bucket_rows``) by repeating the last row, threads a row-validity
mask (``VALID_COL``) through the transform, and drops the pad tail after
finalize — so predictions over the padded batch are bitwise-identical to
the unpadded ones while the predict path compiles once per BUCKET.  The
``compile_events`` counter ticks once per distinct dispatched row shape:
flat after warmup = the compile cache is being hit (the tf.data /
XLA-bucketing recipe, arxiv 2101.12127).
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Union

import numpy as np
import pyarrow as pa

from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.obs.metrics import inc
from sntc_tpu.obs.trace import span
from sntc_tpu.resilience.device import classify_device_error
from sntc_tpu.resilience.faults import fault_point

# row-validity mask column threaded through bucketed transforms: True for
# real rows, False for bucket-padding rows.  Row-DROPPING stages
# (handleInvalid='skip') filter it in lockstep with every other column,
# so finalize recovers exactly the surviving real rows even when the
# stage dropped some.
VALID_COL = "__sntc_row_valid"


def _eager_transform(model: Transformer, frame: Frame) -> Frame:
    """The whole-model HOST path: fused segments run their eager
    stage-by-stage transform (``FusedSegment._transform_eager``),
    everything else its plain ``transform`` — no jitted program, no
    device dispatch.  The compute-plane fault domain serves poisoned
    signatures and HOST_DEGRADED batches through this."""
    from sntc_tpu.core.base import PipelineModel
    from sntc_tpu.fuse import FusedSegment

    if isinstance(model, FusedSegment):
        return model._transform_eager(frame)
    if isinstance(model, PipelineModel):
        out = frame
        for stage in model.getStages():
            out = _eager_transform(stage, out)
        return out
    return model.transform(frame)


def bucket_rows_for(n_rows: int, floor: int) -> int:
    """The padded row count for an ``n_rows`` batch: the next power of
    two, but never below ``floor`` (so tiny ragged batches share one
    bucket).  ``floor <= 0`` disables bucketing (identity)."""
    if floor <= 0 or n_rows <= 0:
        return n_rows
    b = 1 << max(0, int(floor) - 1).bit_length()  # next pow2 >= floor
    while b < n_rows:
        b <<= 1
    return b


class BatchPredictor:
    """Wrap a fitted model/pipeline for Arrow-batch inference.

    ``bucket_rows=N`` arms shape-bucketed dispatch (pad to power-of-two
    row buckets with floor N; 0 = off).  ``compile_events`` counts the
    distinct row shapes this predictor has dispatched — each one costs
    (at most) one XLA compile of the predict program, so a counter that
    stays flat across varying batch sizes is the cache-hit evidence the
    bench journals.
    """

    def __init__(
        self,
        model: Transformer,
        chunk_rows: int = 131_072,
        bucket_rows: int = 0,
        device_domain=None,
    ):
        self.model = model
        self.chunk_rows = int(chunk_rows)
        self.bucket_rows = int(bucket_rows)
        self.compile_events = 0  # distinct dispatched row shapes
        self.bucket_hits = 0  # dispatches that reused a seen shape
        self.padded_rows_total = 0  # wasted rows the buckets cost
        self._shapes_seen: set = set()
        # compute-plane fault domain (r18): classify device/XLA errors
        # at the dispatch boundary and respond per kind — OOM splits
        # the micro-batch, a failed compile poisons the shape, a lost
        # device flips HOST_DEGRADED (eager host serving until the
        # recovery probe succeeds).  None = pre-r18 raise-through.
        self.device_domain = device_domain
        self._poisoned_shapes: set = set()
        # the OOM responder's floor step-down is transient, not a
        # ratchet: remember the cold floor and restore it after
        # `floor_restore_after` clean dispatches (policy)
        self._cold_bucket_rows = self.bucket_rows
        self._clean_streak = 0
        if device_domain is not None:
            self._attach_domain(model)
        # oversized-frame window refills dispatch from inside finalize,
        # which the pipelined engine runs on its delivery thread — the
        # shape ledger must tolerate concurrent dispatchers
        import threading

        self._ledger_lock = threading.Lock()

    def _attach_domain(self, model: Transformer) -> None:
        """Hand the fault domain to every fused segment in ``model``
        so segment-level compile failures poison per (segment,
        signature) and HOST_DEGRADED diverts the fused programs to
        their eager path."""
        from sntc_tpu.fuse import attach_device_domain

        attach_device_domain(model, self.device_domain)

    def swap_model(self, model: Transformer) -> Transformer:
        """Hot-swap the wrapped model IN PLACE, keeping the shape /
        compile ledger and bucket config (the lifecycle hot-swap: the
        ledger's flatness across a swap is the evidence that the new
        model reused the incumbent's compiled programs).  Dispatches
        already in flight finalize against the OLD model — their
        closures bound it at dispatch time; the engine only calls this
        between micro-batches.  Returns the replaced model."""
        old, self.model = self.model, model
        if self.device_domain is not None:
            self._attach_domain(model)
            # predictor-level poisons belong to the REPLACED model's
            # predict programs (the fused-segment poison maps live on
            # the old model's segments and leave with it) — the fresh
            # model earns a clean device plan cache, or a promotion
            # could never lift a shape off the host floor.  The
            # domain's live poisoned-signatures gauge drops by every
            # pair that just left serving.
            from sntc_tpu.fuse import fused_segments

            cleared = len(self._poisoned_shapes) + sum(
                len(s._poisoned) for s in fused_segments(old)
            )
            with self._ledger_lock:
                self._poisoned_shapes.clear()
            if cleared:
                self.device_domain.note_unpoisoned(cleared)
        return old

    # -- bucketed dispatch --------------------------------------------------

    def _record_shape(self, n_rows: int, padded: int = 0) -> None:
        with self._ledger_lock:
            if n_rows in self._shapes_seen:
                self.bucket_hits += 1
                fresh = False
            else:
                self._shapes_seen.add(n_rows)
                self.compile_events += 1
                fresh = True
            self.padded_rows_total += padded
        # mirror into the metrics plane (sntc_predict_* series): the
        # per-predictor attributes stay the legacy views the bench and
        # the daemon's recompiles_after_warmup() already read
        inc(
            "sntc_predict_compile_events_total"
            if fresh else "sntc_predict_bucket_hits_total"
        )
        if padded:
            inc("sntc_predict_padded_rows_total", padded)

    def _dispatch_one(
        self,
        frame: Frame,
        row_valid: "np.ndarray | None" = None,
        model=None,
        _oom_depth: int = 0,
    ) -> Callable[[], Frame]:
        """Dispatch ONE at-most-chunk_rows frame through the model's
        async transform, bucket-padded when armed; the returned finalize
        strips the pad tail via the validity mask.

        ``row_valid`` is the admission layer's salvage mask (True =
        admitted row): excised rows ride INSIDE the dispatched frame —
        already sanitized by the contract — and are filtered at
        finalize through the same ``VALID_COL`` mechanism as bucket
        padding, so salvage never changes the dispatched shape and the
        jitted programs never recompile (``compile_events`` stays
        flat).

        With a :class:`~sntc_tpu.resilience.device.DeviceFaultDomain`
        armed, device/XLA errors classify and respond per kind instead
        of raising through: OOM recursively halves the batch (floored
        at the bucket minimum) and steps the bucket floor down; a
        compile failure poisons the dispatched shape and serves the
        eager host fallback; a lost device flips HOST_DEGRADED."""
        dom = self.device_domain
        if model is None:
            model = self.model
        if dom is not None and dom.host_degraded:
            return self._fallback_dispatch(frame, row_valid, model)
        n = frame.num_rows
        target = bucket_rows_for(n, self.bucket_rows)
        all_admitted = row_valid is None or bool(np.all(row_valid))
        plain = (target == n or n == 0) and all_admitted
        shape = n if plain else target
        if shape in self._poisoned_shapes:
            return self._fallback_dispatch(
                frame, row_valid, model, poisoned=True
            )
        # a fused segment can ABSORB a compile failure inside this
        # dispatch (poison + eager fallback, no exception escapes):
        # such a dispatch "succeeds" but must not reset the domain's
        # consecutive-fault streak — degradation would otherwise
        # depend on which layer the same fault surfaced at
        faults_before = dom.fault_count() if dom is not None else 0
        try:
            # the DEVICE fault boundaries: a fresh shape is (at most)
            # one XLA compile of the predict program; every dispatch
            # touches the device.  Unarmed these are dict misses.
            if n and shape not in self._shapes_seen:
                fault_point("predict.compile")
            fault_point("device.dispatch")
            if plain:
                self._record_shape(n)
                fin = model.transform_async(frame)
            else:
                self._record_shape(target, padded=target - n)
                with span("predict.bucket", rows=n, bucket=target):
                    from sntc_tpu.kernels.assemble import pad_assemble

                    valid = np.zeros(target, dtype=bool)
                    valid[:n] = True if row_valid is None else row_valid
                    # kernel-tier twin of frame.pad_rows(target)
                    # .with_column(VALID_COL, valid) — bitwise, guarded,
                    # poison-laddered (sntc_tpu/kernels/assemble.py)
                    padded = pad_assemble(frame, target, valid)
                inner = model.transform_async(padded)

                def fin() -> Frame:
                    out = inner()
                    mask = np.asarray(out[VALID_COL])
                    out = out.drop(VALID_COL)
                    # a row-dropping stage (handleInvalid='skip') may
                    # have filtered the padded frame: the mask column
                    # was filtered in lockstep, so it still marks
                    # exactly the surviving real rows
                    if mask.all():
                        return out
                    return out.filter(mask)

        except Exception as e:
            if dom is None:
                raise
            kind = classify_device_error(e)
            if kind is None:
                raise
            return self._respond_device(
                kind, e, frame, row_valid, model, shape, _oom_depth
            )
        if dom is not None and dom.fault_count() == faults_before:
            dom.note_success()
            if self.bucket_rows != self._cold_bucket_rows:
                # clean-streak restoration: the OOM pressure passed —
                # give small batches their shared buckets back
                self._clean_streak += 1
                if self._clean_streak >= dom.policy.floor_restore_after:
                    dom.note_bucket_restore(
                        self.bucket_rows, self._cold_bucket_rows
                    )
                    self.bucket_rows = self._cold_bucket_rows
                    self._clean_streak = 0
        return fin

    # -- the device response ladder (resilience/device) ---------------------

    def _respond_device(
        self, kind: str, exc: BaseException, frame: Frame,
        row_valid, model, shape: int, depth: int,
    ) -> Callable[[], Frame]:
        """Per-kind response to a classified device failure (module:
        docs/RESILIENCE.md "Compute-plane fault domain")."""
        dom = self.device_domain
        if kind == "device_oom":
            self._clean_streak = 0
            n = frame.num_rows
            floor = max(1, self.bucket_rows)
            if n > floor and depth < dom.policy.oom_split_depth:
                # split in half, retry ON DEVICE at the smaller shape;
                # halves that still OOM split again until the floor.
                # The bucket floor steps down ONCE per top-level
                # dispatch (not once per recursion level — a 3-deep
                # split must not cost floor/8)
                dom.note_oom_split(
                    rows=n, depth=depth, bucket_floor=self.bucket_rows
                )
                if depth == 0:
                    self._step_bucket_floor()
                mid = (n + 1) // 2
                lmask = None if row_valid is None else row_valid[:mid]
                rmask = None if row_valid is None else row_valid[mid:]
                left = self._dispatch_one(
                    frame.slice(0, mid), lmask, model=model,
                    _oom_depth=depth + 1,
                )
                right = self._dispatch_one(
                    frame.slice(mid, n), rmask, model=model,
                    _oom_depth=depth + 1,
                )
                return lambda: Frame.concat_all([left(), right()])
            # at the floor and still OOM: that is a platform fault, not
            # a splittable batch — count it toward degradation
            dom.note_fault(
                kind, site="device.dispatch", rows=frame.num_rows,
            )
            if dom.host_degraded:
                return self._fallback_dispatch(frame, row_valid, model)
            try:  # already counted: the engine must not double-book it
                exc._sntc_device_counted = True
            except Exception:
                pass
            raise exc
        if kind == "compile_error":
            # poison exactly this dispatched shape: later batches in
            # the same bucket take the host path; other shapes keep
            # compiling on device
            with self._ledger_lock:
                fresh = shape not in self._poisoned_shapes
                self._poisoned_shapes.add(shape)
            if fresh:
                dom.note_poisoned(
                    site="predict.compile", signature=f"rows={shape}",
                    reason=repr(exc),
                )
            dom.note_fault(kind, site="predict.compile")
            return self._fallback_dispatch(
                frame, row_valid, model, poisoned=True
            )
        # device_lost: the domain degrades immediately; serve this
        # dispatch (and everything after it) through the host path
        dom.note_fault(kind, site="device.dispatch")
        return self._fallback_dispatch(frame, row_valid, model)

    def _step_bucket_floor(self) -> None:
        """OOM pressure response: halve the shape-bucket floor (never
        below the policy minimum) so small batches stop padding up to
        a bucket the device cannot hold."""
        dom = self.device_domain
        if self.bucket_rows <= dom.policy.bucket_floor_min:
            return
        new = max(dom.policy.bucket_floor_min, self.bucket_rows // 2)
        if new != self.bucket_rows:
            dom.note_bucket_floor(self.bucket_rows, new)
            self.bucket_rows = new

    def _fallback_dispatch(
        self, frame: Frame, row_valid, model, poisoned: bool = False,
    ) -> Callable[[], Frame]:
        """The eager HOST path: no bucket padding, no device fault
        surface, fused segments divert to their stage-by-stage eager
        transform (they carry the same domain).  Output is pinned
        bitwise against the device path for f64-preserving stages and
        at documented tolerances for f32 device-cast stages
        (docs/RESILIENCE.md tolerance table)."""
        dom = self.device_domain
        if dom is not None:
            dom.note_fallback(poisoned=poisoned)
        all_admitted = row_valid is None or bool(np.all(row_valid))
        if all_admitted:
            def finalize() -> Frame:
                return _eager_transform(model, frame)

            return finalize
        valid = np.asarray(row_valid, dtype=bool)
        carried = frame.with_column(VALID_COL, valid)

        def finalize() -> Frame:
            out = _eager_transform(model, carried)
            mask = np.asarray(out[VALID_COL])
            out = out.drop(VALID_COL)
            if mask.all():
                return out
            return out.filter(mask)

        return finalize

    @staticmethod
    def _memo(fin: Callable[[], Frame]) -> Callable[[], Frame]:
        """Once-only finalize: the engine's sink retry path re-invokes
        finalize on every delivery attempt and retirement round — the
        memo makes that a cached read instead of a re-materialization
        (and shields transform_async overrides that are not
        re-invocation-safe).  FAILURES are cached too: a predict error
        surfacing inside finalize (possible only on the oversized
        chunk-window path, where late chunks dispatch during finalize)
        re-raises immediately on retry instead of re-running the model
        compute per sink attempt.  Known caveat of that path: such an
        error reaches the engine inside the retire stage and is booked
        against ``sink.write`` (breaker/quarantine site), not
        ``predict.dispatch`` — engine micro-batches are normally far
        below ``chunk_rows``, so this affects only pathological
        oversized batches."""
        cell: List = []

        def wrapper() -> Frame:
            if not cell:
                try:
                    cell.append((True, fin()))
                except BaseException as e:
                    cell.append((False, e))
            ok, val = cell[0]
            if not ok:
                raise val
            return val

        return wrapper

    # -- public surface -----------------------------------------------------

    def predict_frame(
        self, frame: Frame, row_valid: "np.ndarray | None" = None
    ) -> Frame:
        return self.predict_frame_async(frame, row_valid=row_valid)()

    # oversized frames keep at most this many chunk dispatches in
    # flight: chunk_rows exists to bound device memory, and dispatching
    # every chunk up front would hold the whole frame's intermediates
    # resident at once
    CHUNK_WINDOW = 2

    def predict_frame_async(
        self, frame: Frame, row_valid: "np.ndarray | None" = None
    ) -> Callable[[], Frame]:
        """Dispatch without blocking; returns a zero-arg finalize
        producing the output Frame (see Transformer.transform_async).
        ``row_valid`` (the admission salvage mask, True = admitted)
        rides the dispatch shape-preservingly — excised rows are
        filtered only at finalize (see ``_dispatch_one``).  Oversized
        frames dispatch chunk-by-chunk through a small sliding
        window (``CHUNK_WINDOW`` outstanding: chunk i+W dispatches
        before chunk i materializes — overlap without unbounding device
        memory), single finalize, one concat.  The pre-r8 path silently
        fell back to a fully synchronous chunked transform, serializing
        the pipelined engine's overlap away."""
        if row_valid is not None:
            row_valid = np.asarray(row_valid, dtype=bool)
            if row_valid.shape != (frame.num_rows,):
                raise ValueError(
                    f"row_valid has shape {row_valid.shape}, expected "
                    f"({frame.num_rows},)"
                )
        if frame.num_rows <= self.chunk_rows:
            return self._memo(self._dispatch_one(frame, row_valid))
        chunks = [
            frame.slice(s, min(s + self.chunk_rows, frame.num_rows))
            for s in range(0, frame.num_rows, self.chunk_rows)
        ]
        masks = [
            None
            if row_valid is None
            else row_valid[s : min(s + self.chunk_rows, frame.num_rows)]
            for s in range(0, frame.num_rows, self.chunk_rows)
        ]
        # bind the dispatch-time model: later chunks dispatch lazily
        # from finalize(), which may run AFTER a lifecycle hot-swap —
        # one committed batch must never mix two models' predictions
        bound = self.model
        fins: List[Callable[[], Frame]] = [
            self._dispatch_one(c, m, model=bound)
            for c, m in zip(
                chunks[: self.CHUNK_WINDOW], masks[: self.CHUNK_WINDOW]
            )
        ]

        def finalize() -> Frame:
            outs = []
            for i in range(len(chunks)):
                nxt = i + self.CHUNK_WINDOW
                if nxt < len(chunks):  # refill the window, THEN block
                    fins.append(
                        self._dispatch_one(
                            chunks[nxt], masks[nxt], model=bound
                        )
                    )
                outs.append(fins[i]())
            return Frame.concat_all(outs)

        return self._memo(finalize)

    def fusion_stats(self) -> Union[dict, None]:
        """Whole-pipeline-fusion evidence when the wrapped model contains
        fused segments (``sntc_tpu.fuse``): segment count, per-signature
        compile ledger (flat after warmup under shape buckets — padded
        batches reuse the bucket's program), fallbacks, and the process
        transfer ledger.  None for unfused models."""
        from sntc_tpu.fuse import fusion_stats

        return fusion_stats(self.model)

    def predict_batch(
        self, batch: Union[pa.RecordBatch, pa.Table]
    ) -> pa.Table:
        return self.predict_frame(Frame.from_arrow(batch)).to_arrow()

    def predict_batches(
        self, batches: Iterator[pa.RecordBatch]
    ) -> Iterator[pa.Table]:
        for batch in batches:
            yield self.predict_batch(batch)

    __call__ = predict_frame
