"""BatchPredictor — the pandas_udf-style Arrow inference bridge [B:5].

Behavioral spec: SURVEY.md §2.6/§3.4: Spark serves ``model.transform`` row
batches through the executor→Python-worker Arrow socket protocol
(``ArrowPythonRunner``).  Here the bridge is direct: Arrow RecordBatch →
numpy → jitted predict (the model's device compute) → Arrow, chunked to
bound device memory.  No sockets, no serialization boundary — the
"pandas_udf-shaped bridge" of SURVEY.md §5.8 collapsed to a function call.

**Shape buckets** (``bucket_rows > 0``): every distinct micro-batch row
count is a fresh XLA compile of the jitted predict program — a streaming
source that delivers 1017, 1018, 1016 rows per tick recompiles forever.
Bucketing pads each batch up to the next power-of-two row count (no lower
than ``bucket_rows``) by repeating the last row, threads a row-validity
mask (``VALID_COL``) through the transform, and drops the pad tail after
finalize — so predictions over the padded batch are bitwise-identical to
the unpadded ones while the predict path compiles once per BUCKET.  The
``compile_events`` counter ticks once per distinct dispatched row shape:
flat after warmup = the compile cache is being hit (the tf.data /
XLA-bucketing recipe, arxiv 2101.12127).
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Union

import numpy as np
import pyarrow as pa

from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.obs.metrics import inc
from sntc_tpu.obs.trace import span

# row-validity mask column threaded through bucketed transforms: True for
# real rows, False for bucket-padding rows.  Row-DROPPING stages
# (handleInvalid='skip') filter it in lockstep with every other column,
# so finalize recovers exactly the surviving real rows even when the
# stage dropped some.
VALID_COL = "__sntc_row_valid"


def bucket_rows_for(n_rows: int, floor: int) -> int:
    """The padded row count for an ``n_rows`` batch: the next power of
    two, but never below ``floor`` (so tiny ragged batches share one
    bucket).  ``floor <= 0`` disables bucketing (identity)."""
    if floor <= 0 or n_rows <= 0:
        return n_rows
    b = 1 << max(0, int(floor) - 1).bit_length()  # next pow2 >= floor
    while b < n_rows:
        b <<= 1
    return b


class BatchPredictor:
    """Wrap a fitted model/pipeline for Arrow-batch inference.

    ``bucket_rows=N`` arms shape-bucketed dispatch (pad to power-of-two
    row buckets with floor N; 0 = off).  ``compile_events`` counts the
    distinct row shapes this predictor has dispatched — each one costs
    (at most) one XLA compile of the predict program, so a counter that
    stays flat across varying batch sizes is the cache-hit evidence the
    bench journals.
    """

    def __init__(
        self,
        model: Transformer,
        chunk_rows: int = 131_072,
        bucket_rows: int = 0,
    ):
        self.model = model
        self.chunk_rows = int(chunk_rows)
        self.bucket_rows = int(bucket_rows)
        self.compile_events = 0  # distinct dispatched row shapes
        self.bucket_hits = 0  # dispatches that reused a seen shape
        self.padded_rows_total = 0  # wasted rows the buckets cost
        self._shapes_seen: set = set()
        # oversized-frame window refills dispatch from inside finalize,
        # which the pipelined engine runs on its delivery thread — the
        # shape ledger must tolerate concurrent dispatchers
        import threading

        self._ledger_lock = threading.Lock()

    def swap_model(self, model: Transformer) -> Transformer:
        """Hot-swap the wrapped model IN PLACE, keeping the shape /
        compile ledger and bucket config (the lifecycle hot-swap: the
        ledger's flatness across a swap is the evidence that the new
        model reused the incumbent's compiled programs).  Dispatches
        already in flight finalize against the OLD model — their
        closures bound it at dispatch time; the engine only calls this
        between micro-batches.  Returns the replaced model."""
        old, self.model = self.model, model
        return old

    # -- bucketed dispatch --------------------------------------------------

    def _record_shape(self, n_rows: int, padded: int = 0) -> None:
        with self._ledger_lock:
            if n_rows in self._shapes_seen:
                self.bucket_hits += 1
                fresh = False
            else:
                self._shapes_seen.add(n_rows)
                self.compile_events += 1
                fresh = True
            self.padded_rows_total += padded
        # mirror into the metrics plane (sntc_predict_* series): the
        # per-predictor attributes stay the legacy views the bench and
        # the daemon's recompiles_after_warmup() already read
        inc(
            "sntc_predict_compile_events_total"
            if fresh else "sntc_predict_bucket_hits_total"
        )
        if padded:
            inc("sntc_predict_padded_rows_total", padded)

    def _dispatch_one(
        self,
        frame: Frame,
        row_valid: "np.ndarray | None" = None,
        model=None,
    ) -> Callable[[], Frame]:
        """Dispatch ONE at-most-chunk_rows frame through the model's
        async transform, bucket-padded when armed; the returned finalize
        strips the pad tail via the validity mask.

        ``row_valid`` is the admission layer's salvage mask (True =
        admitted row): excised rows ride INSIDE the dispatched frame —
        already sanitized by the contract — and are filtered at
        finalize through the same ``VALID_COL`` mechanism as bucket
        padding, so salvage never changes the dispatched shape and the
        jitted programs never recompile (``compile_events`` stays
        flat)."""
        n = frame.num_rows
        target = bucket_rows_for(n, self.bucket_rows)
        all_admitted = row_valid is None or bool(np.all(row_valid))
        if model is None:
            model = self.model
        if (target == n or n == 0) and all_admitted:
            self._record_shape(n)
            return model.transform_async(frame)
        self._record_shape(target, padded=target - n)
        with span("predict.bucket", rows=n, bucket=target):
            valid = np.zeros(target, dtype=bool)
            valid[:n] = True if row_valid is None else row_valid
            padded = frame.pad_rows(target).with_column(VALID_COL, valid)
        fin = model.transform_async(padded)

        def finalize() -> Frame:
            out = fin()
            mask = np.asarray(out[VALID_COL])
            out = out.drop(VALID_COL)
            # a row-dropping stage (handleInvalid='skip') may have
            # filtered the padded frame: the mask column was filtered in
            # lockstep, so it still marks exactly the surviving real rows
            if mask.all():
                return out
            return out.filter(mask)

        return finalize

    @staticmethod
    def _memo(fin: Callable[[], Frame]) -> Callable[[], Frame]:
        """Once-only finalize: the engine's sink retry path re-invokes
        finalize on every delivery attempt and retirement round — the
        memo makes that a cached read instead of a re-materialization
        (and shields transform_async overrides that are not
        re-invocation-safe).  FAILURES are cached too: a predict error
        surfacing inside finalize (possible only on the oversized
        chunk-window path, where late chunks dispatch during finalize)
        re-raises immediately on retry instead of re-running the model
        compute per sink attempt.  Known caveat of that path: such an
        error reaches the engine inside the retire stage and is booked
        against ``sink.write`` (breaker/quarantine site), not
        ``predict.dispatch`` — engine micro-batches are normally far
        below ``chunk_rows``, so this affects only pathological
        oversized batches."""
        cell: List = []

        def wrapper() -> Frame:
            if not cell:
                try:
                    cell.append((True, fin()))
                except BaseException as e:
                    cell.append((False, e))
            ok, val = cell[0]
            if not ok:
                raise val
            return val

        return wrapper

    # -- public surface -----------------------------------------------------

    def predict_frame(
        self, frame: Frame, row_valid: "np.ndarray | None" = None
    ) -> Frame:
        return self.predict_frame_async(frame, row_valid=row_valid)()

    # oversized frames keep at most this many chunk dispatches in
    # flight: chunk_rows exists to bound device memory, and dispatching
    # every chunk up front would hold the whole frame's intermediates
    # resident at once
    CHUNK_WINDOW = 2

    def predict_frame_async(
        self, frame: Frame, row_valid: "np.ndarray | None" = None
    ) -> Callable[[], Frame]:
        """Dispatch without blocking; returns a zero-arg finalize
        producing the output Frame (see Transformer.transform_async).
        ``row_valid`` (the admission salvage mask, True = admitted)
        rides the dispatch shape-preservingly — excised rows are
        filtered only at finalize (see ``_dispatch_one``).  Oversized
        frames dispatch chunk-by-chunk through a small sliding
        window (``CHUNK_WINDOW`` outstanding: chunk i+W dispatches
        before chunk i materializes — overlap without unbounding device
        memory), single finalize, one concat.  The pre-r8 path silently
        fell back to a fully synchronous chunked transform, serializing
        the pipelined engine's overlap away."""
        if row_valid is not None:
            row_valid = np.asarray(row_valid, dtype=bool)
            if row_valid.shape != (frame.num_rows,):
                raise ValueError(
                    f"row_valid has shape {row_valid.shape}, expected "
                    f"({frame.num_rows},)"
                )
        if frame.num_rows <= self.chunk_rows:
            return self._memo(self._dispatch_one(frame, row_valid))
        chunks = [
            frame.slice(s, min(s + self.chunk_rows, frame.num_rows))
            for s in range(0, frame.num_rows, self.chunk_rows)
        ]
        masks = [
            None
            if row_valid is None
            else row_valid[s : min(s + self.chunk_rows, frame.num_rows)]
            for s in range(0, frame.num_rows, self.chunk_rows)
        ]
        # bind the dispatch-time model: later chunks dispatch lazily
        # from finalize(), which may run AFTER a lifecycle hot-swap —
        # one committed batch must never mix two models' predictions
        bound = self.model
        fins: List[Callable[[], Frame]] = [
            self._dispatch_one(c, m, model=bound)
            for c, m in zip(
                chunks[: self.CHUNK_WINDOW], masks[: self.CHUNK_WINDOW]
            )
        ]

        def finalize() -> Frame:
            outs = []
            for i in range(len(chunks)):
                nxt = i + self.CHUNK_WINDOW
                if nxt < len(chunks):  # refill the window, THEN block
                    fins.append(
                        self._dispatch_one(
                            chunks[nxt], masks[nxt], model=bound
                        )
                    )
                outs.append(fins[i]())
            return Frame.concat_all(outs)

        return self._memo(finalize)

    def fusion_stats(self) -> Union[dict, None]:
        """Whole-pipeline-fusion evidence when the wrapped model contains
        fused segments (``sntc_tpu.fuse``): segment count, per-signature
        compile ledger (flat after warmup under shape buckets — padded
        batches reuse the bucket's program), fallbacks, and the process
        transfer ledger.  None for unfused models."""
        from sntc_tpu.fuse import fusion_stats

        return fusion_stats(self.model)

    def predict_batch(
        self, batch: Union[pa.RecordBatch, pa.Table]
    ) -> pa.Table:
        return self.predict_frame(Frame.from_arrow(batch)).to_arrow()

    def predict_batches(
        self, batches: Iterator[pa.RecordBatch]
    ) -> Iterator[pa.Table]:
        for batch in batches:
            yield self.predict_batch(batch)

    __call__ = predict_frame
