"""Elastic serve fleet — a coordinator/worker plane over N processes (r19).

Every survival plane before this one lives inside ONE process on one
device.  This module is the horizontal story: one **coordinator**
supervising N **worker** processes, each running a plain
:class:`~sntc_tpu.serve.tenancy.ServeDaemon` over its assigned slice of
tenants.  Everything is filesystem-coordinated under one *fleet root* —
no sockets, no new dependencies — following the driver/executor shape
of MLlib and the process-rank/heartbeat discipline of MPI-style
distributed training:

* **Placement** — consistent hashing over tenant ids
  (:class:`ConsistentHashRing`: sha1 vnode ring) with the DRR
  weights/quotas as placement *costs* and a bounded-load capacity per
  worker (``slack × total_cost / n_workers``), so a worker joining or
  leaving reshuffles only the tenants that must move.
  ``TenantSpec.placement_cost`` overrides the weight;
  ``TenantSpec.pinned_worker`` nails a tenant to one worker.
* **Liveness** — each worker renews a lease marker
  (``fleet/workers/<id>/lease.json``, through the ``fleet.lease`` fault
  point) carrying its heartbeat payload (rows committed, tenants
  served, applied epoch).  A dedicated heartbeat thread keeps renewing
  while the serving thread sits inside a minutes-long model compile,
  so a slow worker never reads as dead.  The coordinator declares a
  worker whose lease outlives ``lease_ttl_s`` DEAD and redistributes
  its tenants — but a dead source's tree only ships after the lease
  stays expired an extra ``dead_grace_s`` AND a final lease re-read
  shows no renewal (the fencing discipline), and that tree is retired
  into ``fleet/retired/`` rather than deleted, so a zombie's writes
  are never destroyed.
* **Migration is first-class** — rebalancing and dead-worker recovery
  ride ONE code path: the coordinator marks the tenant ``draining``
  (the source worker settles it through the PR 2/7 drain machinery and
  writes a release marker; a dead source skips the drain — its tree is
  crash-consistent by the WAL contract), ships the tenant's
  fsck-verifiable state tree into ``<dst>/tenant/<id>.shipping`` with a
  sealed sha256 manifest (``fleet.migrate`` fires per shipped file),
  verifies manifest + fsck, atomically renames the tree into place, and
  flips the assignment epoch.  The destination daemon resumes through
  the proven WAL-replay restart-convergence path.  A torn ship
  quarantines the partial copy and the tenant re-resumes at the source
  — **migration never loses a committed row** (sink dirs are shared
  absolute paths and the sink dedupes batch replay).
* **Assignment** — the coordinator publishes epochs atomically
  (``fleet/assignments.json``, through ``fleet.assign``) and journals
  every epoch to ``fleet/assignments.jsonl``; workers apply the delta
  (add = :meth:`ServeDaemon.add_tenant`, remove = per-tenant drain +
  release marker + :meth:`ServeDaemon.remove_tenant`).
* **The controller's fleet rungs** — a worker installs
  ``daemon.fleet_hook``; the SLO controller's ``migrate`` /
  ``scale_out`` knobs post requests to
  ``fleet/workers/<id>/requests.jsonl``, which the coordinator consumes
  per tick.

``docs/RESILIENCE.md`` ("Elastic serve fleet") documents the lease
state machine, the migration contract, the fleet flags, and the kill
points; ``scripts/check_fleet_flags.py`` pins CLI ⇔ kwargs ⇔ docs in
tier-1.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import shutil
import threading
import time
from bisect import bisect_right
from typing import Any, Callable, Dict, List, Optional, Tuple

from sntc_tpu.obs.metrics import inc, set_gauge
from sntc_tpu.resilience import emit_event, fault_point
from sntc_tpu.resilience import storage as _storage
from sntc_tpu.serve.tenancy import ServeDaemon, TenantSpec

FLEET_DIR = "fleet"
WORKERS_DIR = "workers"
WORKER_TREES = "worker"
LEASE_MARKER = "lease.json"
ASSIGN_MARKER = "assignments.json"
ASSIGN_JOURNAL = "assignments.jsonl"
REQUESTS_JOURNAL = "requests.jsonl"
RELEASE_DIR = "release"
MIGRATIONS_DIR = "migrations"
RETIRED_DIR = "retired"
FLEET_DRAIN_MARKER = "fleet_drain_marker.json"
COORDINATOR_MARKER = "coordinator.json"

DEFAULT_VNODES = 64
DEFAULT_SLACK = 1.25
DEFAULT_LEASE_TTL_S = 5.0
#: a configured worker that has never heartbeat gets this long to boot
#: (subprocess spawn + backend import dwarf the steady-state TTL)
DEFAULT_BOOT_GRACE_S = 30.0
#: the worker's dedicated heartbeat-thread cadence: leases renew even
#: while the serving thread sits inside a minutes-long model compile,
#: so a SLOW worker is never declared dead — only a silent one
DEFAULT_HEARTBEAT_S = 1.0
#: a migration that keeps failing verification is abandoned (phase
#: ``failed``) after this many ship attempts
MAX_SHIP_ATTEMPTS = 3
#: worker ids the metric plane reserves (the fleet-wide aggregate row
#: is published as ``worker="fleet"``; a real worker under that name
#: would silently collide with it)
RESERVED_WORKER_IDS = frozenset({"fleet"})


def validate_worker_id(worker_id: str) -> str:
    if not worker_id or "/" in worker_id or os.sep in worker_id:
        raise ValueError(
            f"worker_id must be a non-empty path-safe string, got "
            f"{worker_id!r}"
        )
    if worker_id in RESERVED_WORKER_IDS:
        raise ValueError(
            f"worker_id {worker_id!r} is reserved for the fleet-wide "
            "metric aggregate"
        )
    return worker_id


def fleet_meta_dir(root: str) -> str:
    return os.path.join(root, FLEET_DIR)


def worker_root(root: str, worker_id: str) -> str:
    """One worker's ServeDaemon root (its tenant trees live under it)."""
    return os.path.join(root, WORKER_TREES, worker_id)


def worker_meta_dir(root: str, worker_id: str) -> str:
    return os.path.join(root, FLEET_DIR, WORKERS_DIR, worker_id)


def lease_path(root: str, worker_id: str) -> str:
    return os.path.join(worker_meta_dir(root, worker_id), LEASE_MARKER)


def tenant_tree(root: str, worker_id: str, tenant_id: str) -> str:
    return os.path.join(worker_root(root, worker_id), "tenant", tenant_id)


def placement_cost(spec: TenantSpec) -> float:
    """The tenant's bounded-load capacity cost: its declared
    ``placement_cost``, defaulting to its DRR weight."""
    c = spec.placement_cost
    return float(c if c is not None else spec.weight)


class ConsistentHashRing:
    """A sha1 vnode ring with bounded-load assignment.

    ``assign`` places tenants (descending cost, ties by id — fully
    deterministic) at the first ring-order worker whose load stays
    within ``slack × total_cost / n_workers``; the classic
    consistent-hashing property bounds the reshuffle when a worker
    joins or leaves to roughly its own share."""

    def __init__(self, workers: List[str], *, vnodes: int = DEFAULT_VNODES):
        self.workers = sorted(set(workers))
        self.vnodes = int(vnodes)
        self._points: List[Tuple[int, str]] = sorted(
            (self._hash(f"{w}#{i}"), w)
            for w in self.workers for i in range(self.vnodes)
        )
        self._keys = [p[0] for p in self._points]

    @staticmethod
    def _hash(s: str) -> int:
        return int.from_bytes(
            hashlib.sha1(s.encode()).digest()[:8], "big"
        )

    def preference(self, tenant_id: str) -> List[str]:
        """Every worker, in ring order from the tenant's hash point."""
        if not self._points:
            return []
        i = bisect_right(self._keys, self._hash(tenant_id))
        n = len(self._points)
        seen: set = set()
        out: List[str] = []
        for k in range(n):
            w = self._points[(i + k) % n][1]
            if w not in seen:
                seen.add(w)
                out.append(w)
                if len(out) == len(self.workers):
                    break
        return out

    def capacity(
        self, costs: Dict[str, float], *, slack: float = DEFAULT_SLACK
    ) -> float:
        if not self.workers:
            return 0.0
        total = sum(costs.values()) or 1.0
        cap = slack * total / len(self.workers)
        # one tenant must always fit SOMEWHERE, however heavy
        return max(cap, max(costs.values(), default=1.0))

    def assign(
        self,
        costs: Dict[str, float],
        *,
        pinned: Optional[Dict[str, str]] = None,
        slack: float = DEFAULT_SLACK,
    ) -> Dict[str, str]:
        """Bounded-load placement: ``{tenant_id: worker_id}``."""
        if not self.workers:
            return {}
        pinned = pinned or {}
        cap = self.capacity(costs, slack=slack)
        load = {w: 0.0 for w in self.workers}
        out: Dict[str, str] = {}
        order = sorted(
            costs, key=lambda t: (t not in pinned, -costs[t], t)
        )
        for tid in order:
            c = costs[tid]
            if tid in pinned and pinned[tid] in load:
                w = pinned[tid]
            else:
                w = None
                for cand in self.preference(tid):
                    if load[cand] + c <= cap:
                        w = cand
                        break
                if w is None:  # every worker "full": least-loaded
                    w = min(load, key=lambda x: (load[x], x))
            load[w] += c
            out[tid] = w
        return out


# ---------------------------------------------------------------------------
# the worker side
# ---------------------------------------------------------------------------


class FleetWorker:
    """One worker process's runtime: a lazily-built ``ServeDaemon``
    (the daemon needs ≥1 tenant) plus the fleet protocol around it —
    lease renewal, assignment application, release markers, and the
    fleet-request journal the controller's fleet rungs write through.

    ``specs_by_id`` is the full tenant CATALOG; the assignment marker
    says which slice this worker serves.  Clocks are injectable; the
    whole worker is steppable via :meth:`tick` for in-process tests."""

    def __init__(
        self,
        worker_id: str,
        root: str,
        specs_by_id: Dict[str, TenantSpec],
        *,
        daemon_kwargs: Optional[Dict[str, Any]] = None,
        controller: bool = False,
        controller_policy=None,
        heartbeat_interval_s: float = DEFAULT_HEARTBEAT_S,
        clock=time.monotonic,
        wall=time.time,
    ):
        self.worker_id = validate_worker_id(worker_id)
        self.root = root
        self.specs = dict(specs_by_id)
        self.daemon_kwargs = dict(daemon_kwargs or {})
        self.daemon_kwargs.pop("controller", None)
        self.daemon_kwargs.pop("controller_policy", None)
        self._controller_armed = bool(controller)
        self._controller_policy = controller_policy
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self._clock = clock
        self._wall = wall
        self.daemon: Optional[ServeDaemon] = None
        self._seq = 0
        self._epoch = -1
        self._failed: Dict[str, str] = {}  # tid -> error (poisoned spec)
        self._lease_lock = threading.Lock()
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        os.makedirs(self.meta_dir, exist_ok=True)
        os.makedirs(os.path.join(self.meta_dir, RELEASE_DIR),
                    exist_ok=True)

    @property
    def meta_dir(self) -> str:
        return worker_meta_dir(self.root, self.worker_id)

    @property
    def daemon_root(self) -> str:
        return worker_root(self.root, self.worker_id)

    def serving(self) -> List[str]:
        if self.daemon is None:
            return []
        return sorted(t.spec.tenant_id for t in self.daemon.tenants)

    # -- lease --------------------------------------------------------------

    def lease_payload(self) -> Dict[str, Any]:
        d = self.daemon
        tenants = list(d.tenants) if d is not None else []
        return {
            "worker": self.worker_id,
            "pid": os.getpid(),
            "ts": self._wall(),
            "seq": self._seq,
            "epoch": self._epoch,
            "tenants": sorted(t.spec.tenant_id for t in tenants),
            "rows_done": sum(t.rows_done for t in tenants),
            "batches_done": sum(t.batches_done for t in tenants),
            "failed": dict(self._failed),
        }

    def renew_lease(self) -> bool:
        """One heartbeat: the ``fleet.lease`` fault boundary, then the
        atomic lease-marker publish (DEGRADE — a full disk must not
        kill the worker; the coordinator sees the stale lease).
        Serialized, because the dedicated heartbeat thread and the
        tick loop both renew."""
        fault_point("fleet.lease")
        with self._lease_lock:
            self._seq += 1
            return _storage.write_marker(
                lease_path(self.root, self.worker_id),
                self.lease_payload(),
            )

    def start_heartbeat(self) -> bool:
        """Renew the lease from a dedicated daemon thread.  The tick
        loop shares its thread with ``daemon.tick()`` and the
        ``add_tenant`` model compiles — minutes against a seconds-TTL
        lease — so without this a merely SLOW worker reads as dead and
        the coordinator ships a tree the live daemon still writes to.
        The foreground :meth:`run` loop arms it; the steppable test
        path may call it explicitly."""
        if self._hb_thread is not None or self.heartbeat_interval_s <= 0:
            return False
        self._hb_stop.clear()

        def _beat() -> None:
            while not self._hb_stop.wait(self.heartbeat_interval_s):
                try:
                    self.renew_lease()
                except Exception as e:
                    emit_event(
                        event="fleet_lease_error",
                        worker=self.worker_id, error=repr(e),
                    )

        self._hb_thread = threading.Thread(
            target=_beat, name=f"fleet-heartbeat-{self.worker_id}",
            daemon=True,
        )
        self._hb_thread.start()
        return True

    def stop_heartbeat(self) -> None:
        if self._hb_thread is None:
            return
        self._hb_stop.set()
        self._hb_thread.join(timeout=5.0)
        self._hb_thread = None

    # -- fleet requests (the controller's migrate/scale_out rungs) ----------

    def _fleet_request(self, action: str, tenant_id: str,
                       reason: str) -> None:
        rec = {
            "ts": self._wall(),
            "worker": self.worker_id,
            "action": action,
            "tenant": tenant_id,
            "reason": reason,
        }
        path = os.path.join(self.meta_dir, REQUESTS_JOURNAL)
        with open(path, "a") as f:  # storage: fleet_request_journal
            _storage.append_line(
                f, json.dumps(rec) + "\n", site="storage.journal",
                tenant=tenant_id,
            )

    # -- assignment ---------------------------------------------------------

    def read_assignment(self) -> Optional[Dict[str, Any]]:
        path = os.path.join(fleet_meta_dir(self.root), ASSIGN_MARKER)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except (ValueError, OSError):
            # a torn/unreadable marker (the publish is atomic, so this
            # is a dying disk): keep serving the last applied epoch
            return None

    def _start_daemon(self, specs: List[TenantSpec]) -> None:
        self.daemon = ServeDaemon(
            specs, self.daemon_root, **self.daemon_kwargs
        )
        self.daemon.fleet_hook = self._fleet_request
        if self._controller_armed:
            from sntc_tpu.serve.controller import ServeController

            # built AFTER the hook is installed so the fleet rungs
            # attach (the ctor-armed path would see fleet_hook=None)
            self.daemon.controller = ServeController.for_daemon(
                self.daemon, policy=self._controller_policy
            )

    def apply_assignment(
        self, doc: Optional[Dict[str, Any]] = None
    ) -> int:
        """Apply the published assignment delta; returns tenants
        added + removed.  A spec that fails to build marks the tenant
        FAILED in the lease payload (degrade-never-kill) — the
        coordinator stops reassigning it."""
        if doc is None:
            doc = self.read_assignment()
        if doc is None:
            return 0
        epoch = int(doc.get("epoch", -1))
        if epoch <= self._epoch:
            return 0
        mine = {
            tid: e for tid, e in doc.get("tenants", {}).items()
            if e.get("worker") == self.worker_id
            and e.get("phase", "serving") == "serving"
        }
        changed = 0
        # a draining tenant naming THIS worker as source that this
        # worker never held (the previous flip was re-migrated before
        # this worker ever applied it): there is nothing to settle —
        # release immediately, or the coordinator waits on a ghost
        for tid, e in doc.get("tenants", {}).items():
            if (
                e.get("phase") == "draining"
                and e.get("src") == self.worker_id
                and (self.daemon is None
                     or tid not in self.daemon._by_id)
            ):
                _storage.write_marker(
                    os.path.join(
                        self.meta_dir, RELEASE_DIR, f"{tid}.json"
                    ),
                    {"epoch": epoch, "ts": self._wall(), "tenant": tid,
                     "never_held": True},
                    tenant=tid,
                )
        if self.daemon is not None:
            for t in list(self.daemon.tenants):
                tid = t.spec.tenant_id
                if tid in mine:
                    continue
                try:
                    summary = self.daemon.remove_tenant(
                        tid, drain=True, reason=f"reassigned@{epoch}"
                    )
                except Exception as e:
                    emit_event(
                        event="fleet_release_error", tenant=tid,
                        worker=self.worker_id, error=repr(e),
                    )
                    summary = {"tenant": tid, "error": repr(e)}
                _storage.write_marker(
                    os.path.join(
                        self.meta_dir, RELEASE_DIR, f"{tid}.json"
                    ),
                    {"epoch": epoch, "ts": self._wall(), **summary},
                    tenant=tid,
                )
                changed += 1
        for tid in sorted(mine):
            if tid in self._failed or (
                self.daemon is not None
                and tid in self.daemon._by_id
            ):
                continue
            spec = self.specs.get(tid)
            if spec is None:
                self._failed[tid] = "tenant not in this worker's catalog"
                emit_event(
                    event="fleet_spec_missing", tenant=tid,
                    worker=self.worker_id,
                )
                continue
            try:
                if self.daemon is None:
                    self._start_daemon([spec])
                else:
                    self.daemon.add_tenant(spec)
                changed += 1
            except Exception as e:
                # a poisoned spec must not kill the worker — nor leak a
                # half-built daemon (the ctor cleans up after itself)
                if self.daemon is not None and not self.daemon.tenants:
                    self.daemon = None
                self._failed[tid] = repr(e)
                emit_event(
                    event="fleet_spec_failed", tenant=tid,
                    worker=self.worker_id, error=repr(e),
                )
        self._epoch = epoch
        return changed

    # -- the loop -----------------------------------------------------------

    def tick(self) -> int:
        """One worker round: renew the lease, apply any new assignment
        epoch, run one daemon scheduling round.  Every fleet-protocol
        failure degrades (the coordinator's TTL machinery owns the
        consequence); only the daemon's own contracts can raise."""
        try:
            self.renew_lease()
        except Exception as e:
            emit_event(
                event="fleet_lease_error", worker=self.worker_id,
                error=repr(e),
            )
        try:
            self.apply_assignment()
        except Exception as e:
            emit_event(
                event="fleet_assign_error", worker=self.worker_id,
                error=repr(e),
            )
        if self.daemon is None or self.daemon.drained:
            return 0
        return self.daemon.tick()

    def drain_requested(self) -> bool:
        return os.path.exists(
            os.path.join(fleet_meta_dir(self.root), FLEET_DRAIN_MARKER)
        )

    def drain(self, reason: str = "fleet_drain") -> int:
        if self.daemon is None:
            return 0
        self.daemon.request_drain(reason)
        return self.daemon.drain()

    def close(self) -> None:
        if self.daemon is not None:
            self.daemon.close()

    def run(self, poll_interval: float = 0.2) -> Dict[str, Any]:
        """The worker-process foreground loop: tick until SIGTERM or
        the fleet drain marker appears, then drain and exit."""
        import signal as _signal

        stop = threading.Event()
        try:
            _signal.signal(
                _signal.SIGTERM, lambda signum, frame: stop.set()
            )
        except ValueError:  # not the main thread
            pass
        self.start_heartbeat()
        try:
            while not stop.is_set():
                delta = self.tick()
                if self.drain_requested():
                    break
                if delta == 0:
                    stop.wait(poll_interval)
        finally:
            self.drain("fleet_shutdown")
            self.stop_heartbeat()
            status = (
                self.daemon.status() if self.daemon is not None
                else {"tenants": {}}
            )
            self.close()
        return status


# ---------------------------------------------------------------------------
# the coordinator side
# ---------------------------------------------------------------------------


class FleetCoordinator:
    """The fleet's brain: liveness from lease markers, placement from
    the ring, migration (rebalance and dead-worker recovery through ONE
    path), assignment publication, and the fleet metric surface.  Pure
    filesystem + injectable clock — process-agnostic, so tests run it
    in-process against in-process workers while the CLI/bench run it
    against real subprocesses."""

    def __init__(
        self,
        root: str,
        worker_ids: List[str],
        specs_by_id: Dict[str, TenantSpec],
        *,
        lease_ttl_s: float = DEFAULT_LEASE_TTL_S,
        boot_grace_s: float = DEFAULT_BOOT_GRACE_S,
        dead_grace_s: Optional[float] = None,
        vnodes: int = DEFAULT_VNODES,
        slack: float = DEFAULT_SLACK,
        wall=time.time,
        scale_out_hook: Optional[Callable[[str], Optional[str]]] = None,
        standby_root: Optional[str] = None,
    ):
        if not worker_ids:
            raise ValueError("a fleet needs at least one worker id")
        for w in worker_ids:
            validate_worker_id(w)
        self.root = root
        self.specs = dict(specs_by_id)
        self.lease_ttl_s = float(lease_ttl_s)
        self.boot_grace_s = float(boot_grace_s)
        # the ship fence: a DEAD source's tree may only ship after its
        # lease stayed expired this much longer — a slow-but-alive
        # worker gets the window to renew before its tree is taken
        self.dead_grace_s = (
            float(dead_grace_s) if dead_grace_s is not None
            else 2.0 * self.lease_ttl_s
        )
        self.vnodes = int(vnodes)
        self.slack = float(slack)
        self._wall = wall
        self.scale_out_hook = scale_out_hook
        # warm-standby disaster recovery (r23): when a dead worker's
        # primary tree cannot ship (fails fsck / torn), the tenant's
        # replica under <standby_root>/<tid> promotes into the
        # destination instead of the tenant going ``failed``
        self.standby_root = standby_root
        self.epoch = 0
        now = self._wall()
        self.workers: Dict[str, Dict[str, Any]] = {
            w: self._worker_row(now) for w in worker_ids
        }
        #: tid -> {"worker", "phase", and for migrations "src"/"dst"/
        #: "reason"/"attempts"} — phase ∈ serving | draining | failed
        self.assignments: Dict[str, Dict[str, Any]] = {}
        self.migrations = {"completed": 0, "reverted": 0}
        self._dirty = False
        self._draining = False
        self._request_offsets: Dict[str, int] = {}
        self._journal = _storage.RotatingJsonlWriter(
            os.path.join(fleet_meta_dir(self.root), ASSIGN_JOURNAL),
            artifact="fleet_assignment_journal",
        )
        os.makedirs(fleet_meta_dir(self.root), exist_ok=True)
        self._recover()
        # fleet requests are advisory and one-shot: a restarted
        # coordinator must not replay pre-crash migrate/scale_out
        # lines, so start consuming each request journal at its tail
        for wid in self.workers:
            path = os.path.join(
                worker_meta_dir(self.root, wid), REQUESTS_JOURNAL
            )
            try:
                self._request_offsets[wid] = os.path.getsize(path)
            except OSError:
                pass
        if not self.assignments:
            self._bootstrap()
        _storage.write_marker(
            os.path.join(fleet_meta_dir(self.root), COORDINATOR_MARKER),
            {
                "ts": now, "pid": os.getpid(),
                "workers": sorted(self.workers),
                "lease_ttl_s": self.lease_ttl_s,
                "tenants": len(self.specs),
            },
        )

    @staticmethod
    def _worker_row(now: float) -> Dict[str, Any]:
        return {
            "state": "pending", "seq": -1, "ts": None,
            "registered": now, "rows_done": 0, "tenants": 0,
            "epoch": -1, "died_at": None,
        }

    # -- placement ----------------------------------------------------------

    def _live_workers(self) -> List[str]:
        return sorted(
            w for w, row in self.workers.items()
            if row["state"] in ("live", "pending")
        )

    def _costs(self, tenant_ids) -> Dict[str, float]:
        return {
            tid: placement_cost(self.specs[tid])
            for tid in tenant_ids if tid in self.specs
        }

    def _pinned(self) -> Dict[str, str]:
        return {
            tid: s.pinned_worker for tid, s in self.specs.items()
            if s.pinned_worker
        }

    def _ring(self, workers: List[str]) -> ConsistentHashRing:
        return ConsistentHashRing(workers, vnodes=self.vnodes)

    def _bootstrap(self) -> None:
        target = self._ring(self._live_workers()).assign(
            self._costs(self.specs), pinned=self._pinned(),
            slack=self.slack,
        )
        for tid, wid in sorted(target.items()):
            self.assignments[tid] = {"worker": wid, "phase": "serving"}
        self._dirty = True
        self.publish()

    def _choose_dst(self, tenant_id: str,
                    exclude: Tuple[str, ...] = ()) -> Optional[str]:
        """The migration destination: first live worker in the
        tenant's ring preference whose current assigned cost stays
        within capacity; least-loaded live worker otherwise."""
        live = [
            w for w in self._live_workers() if w not in exclude
        ]
        if not live:
            return None
        costs = self._costs(
            tid for tid, e in self.assignments.items()
            if e["phase"] != "failed"
        )
        cost = self._costs([tenant_id]).get(tenant_id, 1.0)
        ring = self._ring(live)
        cap = ring.capacity(costs, slack=self.slack)
        load = {w: 0.0 for w in live}
        for tid, e in self.assignments.items():
            w = e.get("worker")
            if w in load and tid != tenant_id:
                load[w] += costs.get(tid, 0.0)
        for cand in ring.preference(tenant_id):
            if load[cand] + cost <= cap:
                return cand
        return min(load, key=lambda w: (load[w], w))

    # -- liveness -----------------------------------------------------------

    def _read_lease(self, worker_id: str) -> Optional[Dict[str, Any]]:
        path = lease_path(self.root, worker_id)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except (ValueError, OSError):
            return None  # torn lease reads as absent; TTL owns it

    def _check_liveness(self, now: float) -> None:
        for wid, row in sorted(self.workers.items()):
            lease = self._read_lease(wid)
            if lease is not None and int(lease.get("seq", -1)) > row["seq"]:
                renewed = int(lease["seq"]) - max(row["seq"], 0)
                inc(
                    "sntc_fleet_leases_renewed_total",
                    value=renewed, worker=wid,
                )
                row.update(
                    seq=int(lease["seq"]),
                    ts=float(lease.get("ts", now)),
                    rows_done=int(lease.get("rows_done", 0)),
                    tenants=len(lease.get("tenants", ())),
                    epoch=int(lease.get("epoch", -1)),
                )
                for tid, err in (lease.get("failed") or {}).items():
                    self._mark_failed(tid, wid, err)
                if row["state"] != "live":
                    row["state"] = "live"
                    row["died_at"] = None
                    self._dirty = True  # the doc carries worker states
                    emit_event(
                        event="fleet_worker_live", worker=wid,
                        pid=lease.get("pid"),
                    )
                    # a worker that went live holding NOTHING — a
                    # dead-worker rejoin or a scale-out join — earns
                    # its consistent-hash share through migrations
                    if not any(
                        e["phase"] == "serving" and e["worker"] == wid
                        for e in self.assignments.values()
                    ):
                        self.rebalance(reason="join")
            age = now - (
                row["ts"] if row["ts"] is not None else row["registered"]
            )
            ttl = (
                self.lease_ttl_s if row["ts"] is not None
                else max(self.lease_ttl_s, self.boot_grace_s)
            )
            if row["state"] in ("live", "pending") and age > ttl:
                row["state"] = "dead"
                row["died_at"] = now
                inc("sntc_fleet_leases_expired_total", worker=wid)
                emit_event(
                    event="fleet_worker_dead", worker=wid,
                    lease_age_s=round(age, 3), ttl_s=ttl,
                )
                self._recover_worker(wid)

    def _mark_failed(self, tenant_id: str, worker_id: str,
                     error: str) -> None:
        e = self.assignments.get(tenant_id)
        if e is None or e["phase"] == "failed":
            return
        self.assignments[tenant_id] = {
            "worker": None, "phase": "failed", "error": error,
            "last_worker": worker_id,
        }
        emit_event(
            event="fleet_tenant_failed", tenant=tenant_id,
            worker=worker_id, error=error,
        )
        self._dirty = True

    def _recover_worker(self, worker_id: str) -> None:
        """Dead-worker recovery = the migration path with the drain
        skipped (the source cannot drain; its tree is crash-consistent
        by the WAL contract and the restart replays its in-flight
        intent)."""
        for tid in sorted(self.assignments):
            e = self.assignments[tid]
            if e["phase"] == "serving" and e["worker"] == worker_id:
                self.migrate_tenant(tid, reason="worker_dead")
            elif e["phase"] == "draining" and e.get("dst") == worker_id:
                # the destination died mid-migration: re-route
                e["dst"] = None

    # -- migration ----------------------------------------------------------

    def migrate_tenant(
        self, tenant_id: str, dst: Optional[str] = None,
        *, reason: str = "rebalance",
    ) -> bool:
        """Start moving one tenant (the ONE path for rebalancing, the
        controller's migrate rung, and dead-worker recovery).  The
        actual ship happens on a later :meth:`tick`, once the source
        released the tenant (immediately, when the source is dead)."""
        e = self.assignments.get(tenant_id)
        if e is None or e["phase"] != "serving":
            return False
        src = e["worker"]
        if dst is None:
            dst = self._choose_dst(tenant_id, exclude=(src,))
        if dst is None or dst == src:
            emit_event(
                event="fleet_migrate_skipped", tenant=tenant_id,
                src=src, reason="no eligible destination",
            )
            return False
        self.assignments[tenant_id] = {
            "worker": None, "phase": "draining", "src": src,
            "dst": dst, "reason": reason, "attempts": 0,
            "epoch": self.epoch + 1,
        }
        emit_event(
            event="fleet_migrate_start", tenant=tenant_id, src=src,
            dst=dst, reason=reason,
        )
        self._dirty = True
        return True

    def _release_marker(self, worker_id: str, tenant_id: str) -> str:
        return os.path.join(
            worker_meta_dir(self.root, worker_id), RELEASE_DIR,
            f"{tenant_id}.json",
        )

    def _source_released(self, e: Dict[str, Any], tenant_id: str,
                         now: float) -> bool:
        src = e["src"]
        row = self.workers.get(src)
        if row is not None and row.get("state") == "dead":
            # a dead source cannot drain — but "dead" is a TTL verdict,
            # not proof.  Fence before shipping its tree out from under
            # a possibly-still-writing daemon: (1) the lease must stay
            # expired an extra dead_grace_s past the declaration, and
            # (2) a final lease re-read must show no renewal since (a
            # renewal here revives the worker on the next liveness
            # pass, which then drains the tenant properly).
            died_at = row.get("died_at")
            if died_at is None:
                row["died_at"] = now  # adopt: fence from first sight
                return False
            if now - died_at < self.dead_grace_s:
                return False
            lease = self._read_lease(src)
            if lease is not None and int(lease.get("seq", -1)) > row["seq"]:
                return False
            return True
        path = self._release_marker(src, tenant_id)
        if not os.path.exists(path):
            return False
        try:
            with open(path) as f:
                rec = json.load(f)
        except (ValueError, OSError):
            return False
        return int(rec.get("epoch", -1)) >= int(e.get("epoch", 0))

    def _continue_migrations(self, now: float) -> None:
        for tid in sorted(self.assignments):
            e = self.assignments[tid]
            if e["phase"] != "draining":
                continue
            if e.get("dst") is None:
                e["dst"] = self._choose_dst(tid, exclude=(e["src"],))
                if e["dst"] is None:
                    # nowhere to go.  If the SOURCE is back, revert to
                    # it (the torn-ship discipline) instead of leaving
                    # the tenant stranded in draining forever — its
                    # tree at the source is intact until a flip.
                    src = e["src"]
                    if self.workers.get(src, {}).get("state") == "live":
                        self.assignments[tid] = {
                            "worker": src, "phase": "serving",
                        }
                        self._remove_release(src, tid)
                        inc(
                            "sntc_fleet_migrations_total",
                            reason=e.get("reason", "?"),
                            outcome="reverted",
                        )
                        self.migrations["reverted"] += 1
                        emit_event(
                            event="fleet_migrate_reverted", tenant=tid,
                            src=src, dst=None,
                            reason=e.get("reason"),
                            error="no eligible destination",
                            resumed_at=src,
                        )
                        self._dirty = True
                    continue  # retry next tick
                self._dirty = True  # the doc carries the new dst
            if self._source_released(e, tid, now):
                self._ship_and_flip(tid, e)

    def _manifest_path(self, tenant_id: str) -> str:
        return os.path.join(
            fleet_meta_dir(self.root), MIGRATIONS_DIR,
            f"{tenant_id}.json",
        )

    def _ship_tree(self, tenant_id: str, src_tree: str,
                   shipping: str) -> List[List[Any]]:
        """Copy the tenant's state tree file-by-file into the shipping
        dir, hashing as it goes; ``fleet.migrate`` fires before every
        file so a kill/fault anywhere mid-ship leaves a torn copy the
        verifier rejects.  Returns the manifest file rows."""
        if os.path.isdir(shipping):
            shutil.rmtree(shipping)  # a previous attempt's leftovers
        files: List[List[Any]] = []
        for dirpath, dirs, names in os.walk(src_tree):
            dirs[:] = [d for d in dirs if d != ".corrupt"]
            rel_dir = os.path.relpath(dirpath, src_tree)
            os.makedirs(
                os.path.join(shipping, rel_dir)
                if rel_dir != "." else shipping,
                exist_ok=True,
            )
            for name in sorted(names):
                src_f = os.path.join(dirpath, name)
                rel = os.path.normpath(os.path.join(rel_dir, name))
                fault_point("fleet.migrate", tenant=tenant_id)
                with open(src_f, "rb") as f:
                    data = f.read()
                with open(os.path.join(shipping, rel), "wb") as f:
                    f.write(data)
                files.append([
                    rel, len(data), hashlib.sha256(data).hexdigest()
                ])
        return files

    def _verify_shipment(self, manifest: Dict[str, Any],
                         shipping: str) -> None:
        """Re-hash every shipped file against the sealed manifest and
        fsck the shipped checkpoint tree; raises on any mismatch."""
        for rel, size, digest in manifest["files"]:
            path = os.path.join(shipping, rel)
            with open(path, "rb") as f:
                data = f.read()
            if len(data) != size or (
                hashlib.sha256(data).hexdigest() != digest
            ):
                raise _storage.StorageCorruptError(
                    f"shipped file {rel!r} does not match its manifest "
                    "entry"
                )
        ckpt = os.path.join(shipping, "ckpt")
        if os.path.isdir(ckpt):
            report = _storage.fsck_root(
                ckpt, repair=True, tenant=manifest["tenant"]
            )
            if not report["ok"]:
                raise _storage.StorageCorruptError(
                    f"shipped tree failed fsck: {report['errors']}"
                )

    def _quarantine_shipping(self, shipping: str, tenant_id: str,
                             detail: str) -> None:
        if not os.path.isdir(shipping):
            return
        dest_root = os.path.join(self.root, ".corrupt")
        os.makedirs(dest_root, exist_ok=True)
        dest = os.path.join(
            dest_root,
            f"fleet_migration_{tenant_id}_{self.epoch}_{os.getpid()}",
        )
        try:
            if os.path.isdir(dest):
                shutil.rmtree(dest)
            shutil.move(shipping, dest)
        except OSError:
            shutil.rmtree(shipping, ignore_errors=True)
            dest = None
        emit_event(
            event="fleet_ship_quarantined", tenant=tenant_id,
            detail=detail, quarantined_to=dest,
        )

    def _ship_and_flip(self, tenant_id: str, e: Dict[str, Any]) -> None:
        src, dst, reason = e["src"], e["dst"], e.get("reason", "?")
        src_tree = tenant_tree(self.root, src, tenant_id)
        dst_tree = tenant_tree(self.root, dst, tenant_id)
        shipping = dst_tree + ".shipping"
        e["attempts"] = int(e.get("attempts", 0)) + 1
        try:
            if os.path.isdir(src_tree):
                files = self._ship_tree(tenant_id, src_tree, shipping)
                manifest = _storage.seal_record({
                    "tenant": tenant_id, "src": src, "dst": dst,
                    "reason": reason, "epoch": self.epoch + 1,
                    "files": files,
                })
                _storage.atomic_write_json(
                    self._manifest_path(tenant_id), manifest,
                    site="storage.marker", tenant=tenant_id,
                )
                self._verify_shipment(manifest, shipping)
                if os.path.isdir(dst_tree):
                    shutil.rmtree(dst_tree)
                os.rename(shipping, dst_tree)
            # (no src tree = the tenant never reached disk: a fresh
            # start at the destination IS its converged state)
        except Exception as exc:
            self._quarantine_shipping(shipping, tenant_id, repr(exc))
            inc(
                "sntc_fleet_migrations_total", reason=reason,
                outcome="reverted",
            )
            self.migrations["reverted"] += 1
            src_live = (
                self.workers.get(src, {}).get("state") != "dead"
            )
            if src_live:
                # the source still holds the intact tree: the tenant
                # re-resumes THERE — a torn ship must never lose rows
                self.assignments[tenant_id] = {
                    "worker": src, "phase": "serving",
                }
                self._remove_release(src, tenant_id)
            elif self._restore_from_replica(
                tenant_id, dst, dst_tree, error=repr(exc)
            ):
                self._dirty = True
                return
            elif e["attempts"] >= MAX_SHIP_ATTEMPTS:
                self._mark_failed(tenant_id, src, repr(exc))
            emit_event(
                event="fleet_migrate_reverted", tenant=tenant_id,
                src=src, dst=dst, reason=reason, error=repr(exc),
                resumed_at=src if src_live else None,
            )
            self._dirty = True
            return
        # flipped: the destination owns the tenant from this epoch on
        self.assignments[tenant_id] = {"worker": dst, "phase": "serving"}
        self._remove_release(src, tenant_id)
        self._retire_src_tree(tenant_id, src, src_tree)
        inc(
            "sntc_fleet_migrations_total", reason=reason,
            outcome="completed",
        )
        self.migrations["completed"] += 1
        emit_event(
            event="fleet_migrate_done", tenant=tenant_id, src=src,
            dst=dst, reason=reason,
        )
        self._dirty = True

    def _restore_from_replica(
        self, tenant_id: str, dst: str, dst_tree: str, *, error: str,
    ) -> bool:
        """Dead-source recovery of last resort (r23): the primary tree
        could not ship (fsck failure, torn files, unreadable disk) and
        the source is dead — promote the tenant's warm-standby replica
        into the destination tree instead of marking the tenant
        ``failed``.  Returns True when the tenant is serving again."""
        if not self.standby_root:
            return False
        from sntc_tpu.resilience.replicate import (
            promote_standby,
            replica_dir,
        )

        if not os.path.isdir(replica_dir(self.standby_root, tenant_id)):
            return False
        staging = dst_tree + ".restoring"
        shutil.rmtree(staging, ignore_errors=True)
        try:
            rep = promote_standby(self.standby_root, tenant_id, staging)
        except Exception as exc:
            shutil.rmtree(staging, ignore_errors=True)
            emit_event(
                event="fleet_replica_restore_failed", tenant=tenant_id,
                error=repr(exc), ship_error=error,
            )
            return False
        if not rep.get("ok"):
            shutil.rmtree(staging, ignore_errors=True)
            emit_event(
                event="fleet_replica_restore_failed", tenant=tenant_id,
                reason=rep.get("reason"), ship_error=error,
            )
            return False
        if os.path.isdir(dst_tree):
            shutil.rmtree(dst_tree)
        os.rename(staging, dst_tree)
        self.assignments[tenant_id] = {"worker": dst, "phase": "serving"}
        inc(
            "sntc_fleet_migrations_total", reason="replica_restore",
            outcome="completed",
        )
        self.migrations["completed"] += 1
        emit_event(
            event="tenant_restored_from_replica", tenant=tenant_id,
            worker=dst, ship_error=error,
            batches_through=rep.get("batches_through"),
            rto_seconds=rep.get("rto_seconds"),
        )
        return True

    def _remove_release(self, worker_id: str, tenant_id: str) -> None:
        try:
            os.unlink(self._release_marker(worker_id, tenant_id))
        except OSError:
            pass

    def _retire_src_tree(
        self, tenant_id: str, src: str, src_tree: str,
        *, assume_dead: bool = False,
    ) -> None:
        """Dispose of the source copy after a completed flip.  A LIVE
        source acked the move (its release marker carries the epoch) —
        its daemon no longer touches the tree, so deletion is safe.  A
        DEAD source may be a zombie still writing: never destroy its
        bytes — rename the tree aside into ``fleet/retired/`` (out of
        the serving namespace, preserved as evidence; a rename keeps
        the single-home invariant under ``worker/*/tenant/``)."""
        if not os.path.isdir(src_tree):
            return
        if not assume_dead and (
            self.workers.get(src, {}).get("state") != "dead"
        ):
            shutil.rmtree(src_tree, ignore_errors=True)
            return
        dest_root = os.path.join(fleet_meta_dir(self.root), RETIRED_DIR)
        dest = os.path.join(
            dest_root, f"{tenant_id}.{src}.{self.epoch + 1}"
        )
        try:
            os.makedirs(dest_root, exist_ok=True)
            if os.path.isdir(dest):
                shutil.rmtree(dest)
            shutil.move(src_tree, dest)
        except OSError:
            dest = None  # left in place; recovery retries the retire
        emit_event(
            event="fleet_src_tree_retired", tenant=tenant_id,
            worker=src, retired_to=dest,
        )

    # -- fleet requests ------------------------------------------------------

    def _consume_requests(self) -> None:
        for wid in sorted(self.workers):
            path = os.path.join(
                worker_meta_dir(self.root, wid), REQUESTS_JOURNAL
            )
            if not os.path.exists(path):
                continue
            offset = self._request_offsets.get(wid, 0)
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    tail = f.read()
            except OSError:
                continue
            # binary read + newline-bounded cut: the offset is a BYTE
            # position, and a torn (partial) last line stays unconsumed
            # for the next tick rather than being silently dropped —
            # these requests fire at most once per tenant per daemon
            # lifetime, so a lost line is never re-posted
            cut = tail.rfind(b"\n") + 1
            if cut == 0:
                continue
            for line in tail[:cut].splitlines():
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # a genuinely corrupt (mid-file) line
                self._handle_request(rec)
            self._request_offsets[wid] = offset + cut

    def _handle_request(self, rec: Dict[str, Any]) -> None:
        action = rec.get("action")
        tid = rec.get("tenant")
        if action == "migrate":
            self.migrate_tenant(tid, reason="controller")
        elif action == "scale_out":
            emit_event(
                event="fleet_scale_out_requested", tenant=tid,
                worker=rec.get("worker"), reason=rec.get("reason"),
            )
            if self.scale_out_hook is not None:
                try:
                    new_wid = self.scale_out_hook(rec.get("reason", ""))
                except Exception as e:
                    emit_event(
                        event="fleet_scale_out_error", error=repr(e)
                    )
                    return
                if new_wid:
                    try:
                        self.add_worker(new_wid)
                    except ValueError as e:
                        emit_event(
                            event="fleet_scale_out_error", error=repr(e)
                        )

    # -- membership ----------------------------------------------------------

    def add_worker(self, worker_id: str) -> None:
        if worker_id in self.workers:
            return
        validate_worker_id(worker_id)
        self.workers[worker_id] = self._worker_row(self._wall())
        emit_event(event="fleet_worker_added", worker=worker_id)
        self.rebalance(reason="join")

    def rebalance(self, *, reason: str = "rebalance") -> int:
        """Recompute bounded-load placement over the live workers and
        migrate every serving tenant whose target moved (consistent
        hashing bounds how many do)."""
        live = self._live_workers()
        if not live:
            return 0
        serving = [
            tid for tid, e in self.assignments.items()
            if e["phase"] == "serving"
        ]
        target = self._ring(live).assign(
            self._costs(serving), pinned=self._pinned(),
            slack=self.slack,
        )
        moved = 0
        for tid in sorted(target):
            if self.assignments[tid]["worker"] != target[tid]:
                if self.migrate_tenant(
                    tid, target[tid], reason=reason
                ):
                    moved += 1
        return moved

    # -- publish / recover ---------------------------------------------------

    def assignment_doc(self) -> Dict[str, Any]:
        return {
            "epoch": self.epoch,
            "ts": self._wall(),
            "workers": {
                w: row["state"] for w, row in sorted(self.workers.items())
            },
            "tenants": {
                tid: dict(e)
                for tid, e in sorted(self.assignments.items())
            },
        }

    def publish(self) -> bool:
        """Publish the current assignment epoch: the ``fleet.assign``
        fault boundary, one atomic marker write, one journal line."""
        if not self._dirty:
            return False
        self.epoch += 1
        fault_point("fleet.assign")
        doc = self.assignment_doc()
        _storage.atomic_write_json(
            os.path.join(fleet_meta_dir(self.root), ASSIGN_MARKER),
            doc, site="storage.marker",
        )
        self._journal.write(doc)
        self._dirty = False
        return True

    def _recover(self) -> None:
        """Restart convergence: re-adopt the published assignment,
        quarantine any torn mid-ship copies, and put every in-flight
        migration back on the path (the tenant is live on exactly one
        worker after the next few ticks — the kill-mid-migrate
        contract)."""
        path = os.path.join(fleet_meta_dir(self.root), ASSIGN_MARKER)
        if not os.path.exists(path):
            return
        try:
            doc = json.load(open(path))
        except (ValueError, OSError) as e:
            emit_event(
                event="fleet_recover_error", error=repr(e), path=path
            )
            return
        self.epoch = int(doc.get("epoch", 0))
        for tid, e in sorted(doc.get("tenants", {}).items()):
            self.assignments[tid] = dict(e)
        # torn mid-ship copies: the flip is a dir rename AFTER manifest
        # verification, so any *.shipping dir is by construction an
        # unverified partial — quarantine it; its migration entry is
        # still "draining" and will re-ship from the intact source
        for shipping in sorted(glob.glob(
            os.path.join(self.root, WORKER_TREES, "*", "tenant",
                         "*.shipping")
        )):
            tid = os.path.basename(shipping)[: -len(".shipping")]
            self._quarantine_shipping(
                shipping, tid, "torn mid-ship copy found at recovery"
            )
        # a crash between flip and source-tree retirement leaves a
        # stale source copy: the assignment is the truth — retire trees
        # at workers that no longer own the tenant IF a verified
        # manifest records the completed move.  Retire (rename aside),
        # never rmtree: a restarted coordinator has no liveness
        # verdict yet, and the worker could be a zombie mid-write.
        for tid, e in sorted(self.assignments.items()):
            if e.get("phase") != "serving":
                continue
            mpath = self._manifest_path(tid)
            if not os.path.exists(mpath):
                continue
            try:
                manifest = _storage.load_sealed_json(mpath)
            except _storage.StorageCorruptError:
                continue
            if manifest.get("dst") != e.get("worker"):
                continue
            if manifest.get("src"):
                self._retire_src_tree(
                    tid, manifest["src"],
                    tenant_tree(self.root, manifest["src"], tid),
                    assume_dead=True,
                )
        emit_event(
            event="fleet_recovered", epoch=self.epoch,
            tenants=len(self.assignments),
        )

    # -- the loop ------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One coordinator round: liveness, fleet requests, in-flight
        migrations, publish-if-changed, gauges.  Returns a compact
        status row."""
        if now is None:
            now = self._wall()
        if self.draining:
            # the fleet is shutting down: workers exiting on purpose
            # must not read as lease expiries and trigger a final
            # storm of pointless migrations
            self.publish()
            self._publish_gauges()
            return self.status()
        self._check_liveness(now)
        self._consume_requests()
        self._continue_migrations(now)
        self.publish()
        self._publish_gauges()
        return self.status()

    @property
    def draining(self) -> bool:
        if not self._draining and os.path.exists(
            os.path.join(fleet_meta_dir(self.root), FLEET_DRAIN_MARKER)
        ):
            self._draining = True
        return self._draining

    def _publish_gauges(self) -> None:
        total_rows = 0
        for wid, row in sorted(self.workers.items()):
            set_gauge(
                "sntc_fleet_worker_state",
                1 if row["state"] == "live" else 0, worker=wid,
            )
            set_gauge(
                "sntc_fleet_tenants_value",
                sum(
                    1 for e in self.assignments.values()
                    if e.get("worker") == wid and e["phase"] == "serving"
                ),
                worker=wid,
            )
            set_gauge(
                "sntc_fleet_rows_value", row["rows_done"], worker=wid
            )
            if row["state"] == "live":
                total_rows += row["rows_done"]
        set_gauge("sntc_fleet_rows_value", total_rows, worker="fleet")

    def drain_fleet(self, reason: str = "drain") -> None:
        """Raise the fleet drain marker every worker's loop watches."""
        self._draining = True
        _storage.write_marker(
            os.path.join(fleet_meta_dir(self.root), FLEET_DRAIN_MARKER),
            {"ts": self._wall(), "reason": reason, "epoch": self.epoch},
        )
        emit_event(event="fleet_drain", reason=reason)

    def status(self) -> Dict[str, Any]:
        phases: Dict[str, int] = {}
        for e in self.assignments.values():
            phases[e["phase"]] = phases.get(e["phase"], 0) + 1
        return {
            "epoch": self.epoch,
            "workers": {
                w: {
                    "state": row["state"], "rows_done": row["rows_done"],
                    "tenants": sum(
                        1 for e in self.assignments.values()
                        if e.get("worker") == w
                        and e["phase"] == "serving"
                    ),
                }
                for w, row in sorted(self.workers.items())
            },
            "tenants": len(self.assignments),
            "phases": phases,
            "migrations": dict(self.migrations),
        }

    def close(self) -> None:
        # no handles held (the journal opens per append); flush the
        # final state so a restarted coordinator adopts it verbatim
        self._dirty = True
        self.publish()


# ---------------------------------------------------------------------------
# fleet-root fsck (the `sntc fsck --fleet-root` walker)
# ---------------------------------------------------------------------------


def fsck_fleet(root: str, *, repair: bool = True) -> Dict[str, Any]:
    """Doctor a coordinator root: the fleet metadata (assignment
    marker + journal, leases, request journals, migration manifests)
    plus every worker's daemon tree through the standard per-root
    :func:`~sntc_tpu.resilience.storage.fsck`.  Torn journals repair
    through the tolerant-reader discipline; an unrepairable (corrupt
    sealed) migration manifest is an ERROR — ``ok`` goes False and the
    CLI exits 1."""
    fdir = fleet_meta_dir(root)
    report: Dict[str, Any] = {
        "root": root, "fleet": True, "repair": bool(repair),
        "checked": {}, "repaired": [], "quarantined": [], "cleaned": [],
        "errors": [], "workers": {},
    }

    def _checked(kind: str) -> None:
        report["checked"][kind] = report["checked"].get(kind, 0) + 1

    # 1. assignment journal: torn tails repair; mid-file damage
    # quarantines (the atomic marker is the authoritative epoch)
    jpath = os.path.join(fdir, ASSIGN_JOURNAL)
    if os.path.exists(jpath):
        _checked("fleet_assignment_journal")
        try:
            _records, rec = _storage.read_jsonl_tolerant(
                jpath, repair=repair,
                artifact="fleet_assignment_journal", repair_dir=fdir,
            )
            if rec is not None:
                (report["repaired"] if repair
                 else report["errors"]).append(
                    {"path": jpath,
                     "artifact": "fleet_assignment_journal", **rec}
                )
        except _storage.JsonlCorruptError as e:
            q = _storage.quarantine_blob(
                jpath, artifact="fleet_assignment_journal",
                detail=str(e), root=fdir,
            ) if repair else None
            (report["quarantined"] if repair
             else report["errors"]).append(
                {"path": jpath, "detail": str(e),
                 "quarantined_to": q}
            )

    # 2. the assignment marker + coordinator marker + leases + release
    # markers: atomic JSON — unparseable means a dying disk; the lease
    # refreshes on the next heartbeat and the marker on the next
    # publish, so quarantining preserves evidence without data loss
    markers = [
        (os.path.join(fdir, ASSIGN_MARKER), "fleet_assignments"),
        (os.path.join(fdir, COORDINATOR_MARKER), "fleet_markers"),
        (os.path.join(fdir, FLEET_DRAIN_MARKER), "fleet_markers"),
    ]
    markers += [
        (p, "fleet_lease") for p in sorted(glob.glob(
            os.path.join(fdir, WORKERS_DIR, "*", LEASE_MARKER)
        ))
    ]
    markers += [
        (p, "fleet_markers") for p in sorted(glob.glob(
            os.path.join(fdir, WORKERS_DIR, "*", RELEASE_DIR, "*.json")
        ))
    ]
    for path, artifact in markers:
        if not os.path.exists(path):
            continue
        _checked(artifact)
        try:
            with open(path) as f:
                json.load(f)
        except ValueError as e:
            detail = f"unparseable fleet marker: {e}"
            if repair:
                q = _storage.quarantine_blob(
                    path, artifact=artifact, detail=detail, root=fdir,
                )
                report["quarantined"].append(
                    {"path": path, "detail": detail,
                     "quarantined_to": q}
                )
            else:
                report["errors"].append(
                    {"path": path, "detail": detail}
                )

    # 3. request journals: same tolerant-reader discipline
    for path in sorted(glob.glob(
        os.path.join(fdir, WORKERS_DIR, "*", REQUESTS_JOURNAL)
    )):
        _checked("fleet_request_journal")
        try:
            _records, rec = _storage.read_jsonl_tolerant(
                path, repair=repair, artifact="fleet_request_journal",
                repair_dir=fdir,
            )
            if rec is not None:
                (report["repaired"] if repair
                 else report["errors"]).append(
                    {"path": path,
                     "artifact": "fleet_request_journal", **rec}
                )
        except _storage.JsonlCorruptError as e:
            q = _storage.quarantine_blob(
                path, artifact="fleet_request_journal", detail=str(e),
                root=fdir,
            ) if repair else None
            (report["quarantined"] if repair
             else report["errors"]).append(
                {"path": path, "detail": str(e), "quarantined_to": q}
            )

    # 4. migration manifests: SEALED records — a broken seal is not
    # repairable (the history of what moved where is gone); loud error
    for path in sorted(glob.glob(
        os.path.join(fdir, MIGRATIONS_DIR, "*.json")
    )):
        _checked("fleet_migration_manifest")
        try:
            _storage.load_sealed_json(path)
        except _storage.StorageCorruptError as e:
            report["errors"].append(
                {"path": path, "artifact": "fleet_migration_manifest",
                 "detail": str(e)}
            )

    # 5. torn mid-ship copies are by construction unverified partials
    for shipping in sorted(glob.glob(
        os.path.join(root, WORKER_TREES, "*", "tenant", "*.shipping")
    )):
        _checked("shipping_orphans")
        if repair:
            shutil.rmtree(shipping, ignore_errors=True)
            report["cleaned"].append({"path": shipping})
        else:
            report["errors"].append(
                {"path": shipping, "detail": "torn mid-ship copy"}
            )

    # 6. every worker's daemon root, tenant trees included
    for wdir in sorted(glob.glob(
        os.path.join(root, WORKER_TREES, "*")
    )):
        wid = os.path.basename(wdir)
        report["workers"][wid] = _storage.fsck(
            wdir, repair=repair, tenant_tree=True
        )

    # 7. retired dead-source trees (r23): until now these were
    # write-only evidence — verify each one like any tenant tree so a
    # ``fleet-restore-retired`` has a known-good source to copy from
    report["retired"] = {}
    for rdir in sorted(glob.glob(
        os.path.join(fdir, RETIRED_DIR, "*")
    )):
        name = os.path.basename(rdir)
        if not os.path.isdir(rdir) or name.startswith("."):
            continue
        _checked("fleet_retired_tree")
        ckpt = os.path.join(rdir, "ckpt")
        report["retired"][name] = _storage.fsck_root(
            ckpt if os.path.isdir(ckpt) else rdir, repair=repair
        )

    report["ok"] = (
        not report["errors"]
        and all(r["ok"] for r in report["workers"].values())
        and all(r["ok"] for r in report["retired"].values())
    )
    return report


def restore_retired(
    root: str, name: str, dest: str, *, repair: bool = True,
) -> Dict[str, Any]:
    """Recover a retired dead-source tree
    ``fleet/retired/<tid>.<wid>.<epoch>`` into an EXPLICIT destination
    directory (never back into the serving namespace — the operator
    inspects, then re-registers the tenant or merges by hand):
    fsck-verify the tree, copy it file-by-file, publish a sealed
    restore manifest beside the copy, and journal the restore.  This
    is how a wrongly-declared-dead worker's rows come back."""
    src = os.path.join(fleet_meta_dir(root), RETIRED_DIR, name)
    report: Dict[str, Any] = {
        "name": name, "src": src, "dest": dest, "ok": False,
    }
    if not os.path.isdir(src):
        report["error"] = "no such retired tree"
        return report
    ckpt = os.path.join(src, "ckpt")
    fs = _storage.fsck_root(
        ckpt if os.path.isdir(ckpt) else src, repair=repair
    )
    report["fsck"] = fs
    if not fs["ok"]:
        report["error"] = "retired tree fails fsck"
        return report
    files = []
    for dirpath, dirnames, filenames in os.walk(src):
        dirnames[:] = [d for d in dirnames if d != ".corrupt"]
        for fn in sorted(filenames):
            p = os.path.join(dirpath, fn)
            rel = os.path.relpath(p, src)
            with open(p, "rb") as f:
                data = f.read()
            _storage.atomic_write_bytes(
                os.path.join(dest, rel), data, site="storage.marker",
            )
            files.append(
                [rel, len(data), hashlib.sha256(data).hexdigest()]
            )
    manifest = _storage.seal_record({
        "retired": name, "dest": os.path.abspath(dest), "files": files,
    })
    _storage.atomic_write_json(
        os.path.join(dest, "restore_manifest.json"), manifest,
        site="storage.marker",
    )
    emit_event(
        event="fleet_retired_restored", name=name, dest=dest,
        files=len(files),
    )
    report.update(ok=True, files=len(files))
    return report
