from sntc_tpu.serve.transform import BatchPredictor
from sntc_tpu.serve.streaming import (
    ConsoleSink,
    CsvDirSink,
    FileStreamSource,
    MemorySink,
    MemorySource,
    StreamingQuery,
)

__all__ = [
    "BatchPredictor",
    "StreamingQuery",
    "FileStreamSource",
    "MemorySource",
    "MemorySink",
    "CsvDirSink",
    "ConsoleSink",
]
