from sntc_tpu.serve.transform import BatchPredictor
from sntc_tpu.serve.fuse import compile_pipeline, compile_serving
from sntc_tpu.serve.netflow_source import (
    NetFlowDirSource,
    PcapDirSource,
    capture_udp,
)
from sntc_tpu.serve.streaming import (
    ConsoleSink,
    CsvDirSink,
    FileStreamSource,
    MemorySink,
    MemorySource,
    StreamingQuery,
)
from sntc_tpu.serve.controller import (
    ServeController,
    SloPolicy,
    SloSignal,
)
from sntc_tpu.serve.tenancy import (
    ServeDaemon,
    TenantSpec,
    TenantStream,
)
from sntc_tpu.serve.ingress import (
    CsvSpoolSource,
    IngressSpool,
    NetFlowSpoolSource,
    TcpRowIngress,
    UdpIngressListener,
    build_ingress,
    frame_rows,
    wire_committed_offset,
)

__all__ = [
    "ServeController",
    "SloPolicy",
    "SloSignal",
    "BatchPredictor",
    "compile_pipeline",
    "compile_serving",
    "StreamingQuery",
    "FileStreamSource",
    "MemorySource",
    "MemorySink",
    "CsvDirSink",
    "ConsoleSink",
    "NetFlowDirSource",
    "PcapDirSource",
    "capture_udp",
    "ServeDaemon",
    "TenantSpec",
    "TenantStream",
    "IngressSpool",
    "UdpIngressListener",
    "TcpRowIngress",
    "NetFlowSpoolSource",
    "CsvSpoolSource",
    "build_ingress",
    "frame_rows",
    "wire_committed_offset",
]
