"""Multi-tenant serve front door — many streams, one device (r12).

One :class:`~sntc_tpu.serve.streaming.StreamingQuery` owns one model,
one source, and one sink; "millions of users" as N independent
processes means N engines fighting over the device with zero isolation.
:class:`ServeDaemon` multiplexes N :class:`TenantStream`\\ s — each a
pipeline + source + sink + checkpoint dir + row policy — over shared
infrastructure, on ONE scheduling thread, with four contracts:

* **Shared program cache** — tenants handing the daemon the SAME model
  object (or checkpoint path) share one
  :class:`~sntc_tpu.serve.transform.BatchPredictor`, so they share its
  shape-bucketed / fused compiled programs: adding a tenant to an
  already-warm signature costs ZERO compiles, proven by the existing
  compile ledger (``recompiles_after_warmup()``; bench config 8
  journals it across 10+ tenants).
* **Fair scheduling** — a weighted deficit round-robin dispatches
  micro-batches across tenant backlogs: each scheduling round credits
  every runnable tenant ``weight`` batches of deficit and drains it in
  a fixed rotation, so throughput under contention converges to the
  weight ratio.  Per-tenant quotas bound what one tenant can take:
  ``max_rows_per_sec`` (a token bucket charged at commit) throttles a
  flooding source at its own admission edge, ``max_pending_batches`` +
  ``shed_policy`` sheds its backlog through the engine's journaled
  shed path — both leave every other tenant's latency alone.
* **Per-tenant fault isolation** — every site a tenant's engine
  touches is namespaced ``tenant/<id>/...``: breakers
  (``breaker_for``), fault points, retry/quarantine/shed events (all
  tenant-tagged), health components, and the on-disk layout
  (``<root>/tenant/<id>/ckpt/`` with ``dead_letter`` /
  ``dead_letter_rows`` under it, ``drain_marker.json`` beside it).  A
  tenant escalates OK → THROTTLED → QUARANTINED → STOPPED on its OWN
  evidence — UNHEALTHY-class events carrying its tag — and a STOPPED
  tenant's breakers are evicted (``reset_breakers(prefix=...)``) so
  its state cannot leak.  The daemon loop itself never dies for a
  tenant: engine errors strike the tenant, not the process.
* **Drain** — SIGTERM / :meth:`ServeDaemon.request_drain` settles
  every tenant's in-flight work (commit or WAL-replay-on-restart,
  exactly the single-query contract), writes one atomic drain marker
  per tenant plus a daemon-level marker, and exits 0.

Scheduling runs on one thread (the daemon's), so the device sees one
dispatch stream and every engine keeps its single-WAL-writer contract;
the only other threads are the ones the engines already own (overlap
delivery, source prefetch).  The clock is injectable and :meth:`tick`
is steppable — fairness, quotas, and the ladder are all unit-testable
without sleeps.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from dataclasses import dataclass, fields as dc_fields
from typing import Any, Dict, List, Optional

import numpy as np

from sntc_tpu.obs.metrics import inc, registry, set_gauge
from sntc_tpu.obs.trace import span
from sntc_tpu.resilience import (
    HealthState,
    breaker_for,
    emit_event,
    events_dropped,
    reset_breakers,
)
from sntc_tpu.resilience import storage as _storage
from sntc_tpu.resilience.health import HealthMonitor
from sntc_tpu.resilience.policy import RetryPolicy
from sntc_tpu.serve.streaming import (
    CsvDirSink,
    FileStreamSource,
    StreamingQuery,
)
from sntc_tpu.serve.transform import BatchPredictor

#: the tenant escalation ladder, in order.  OK ↔ THROTTLED are the
#: quota states (automatic both ways); QUARANTINED is entered on
#: ``quarantine_after`` unhealthy strikes and left after
#: ``quarantine_cooldown_s`` on probation; STOPPED (after
#: ``stop_after`` quarantine episodes, or a fatal engine error) is
#: terminal for the daemon's lifetime.
TENANT_STATES = ("OK", "THROTTLED", "QUARANTINED", "STOPPED")

#: events that count as an unhealthy STRIKE against the tenant that
#: emitted them (the ladder's escalation evidence), attributed by
#: their ``tenant`` field or their ``tenant/<id>/...`` site.
#: ``retry`` / ``rows_rejected`` / ``load_shed`` deliberately do NOT
#: strike — they are the degraded-but-working vocabulary, already
#: absorbed by throttling and shedding.  ``watchdog_stall`` is not
#: listed: it carries neither tenant nor site, and the daemon never
#: arms the supervisor watchdog (engine wedges surface as
#: ``tenant_error`` strikes from the scheduler instead).
STRIKE_EVENTS = frozenset(
    ("quarantine", "retry_exhausted", "breaker_open")
)

DAEMON_DRAIN_MARKER = "daemon_drain_marker.json"


def _atomic_json(path: str, obj: Dict[str, Any]) -> str:
    from sntc_tpu.resilience.supervisor import _atomic_json as _write

    return _write(path, obj, indent=1)


#: the keys a TenantSpec ``ingress`` block accepts — each one maps to
#: a ``serve.ingress.build_ingress`` kwarg of the same meaning
#: (``scripts/check_ingress_flags.py`` pins the correspondence)
INGRESS_KEYS = frozenset({
    "listen_udp", "listen_tcp", "spool_mb", "ring", "seal_every",
    "seal_idle_s", "keep_files", "columns",
})


@dataclass
class TenantSpec:
    """One tenant's declaration: identity, pipeline, endpoints, quotas,
    and ladder thresholds.  The serve-daemon CLI reads a JSON file of
    these (``--tenants``); daemon-level flags supply defaults for any
    field a tenant omits (``scripts/check_tenant_flags.py`` pins the
    flag ⇔ field ⇔ docs mapping in tier-1).

    ``model`` is a fitted Transformer, a ``BatchPredictor``, or a
    checkpoint path — tenants passing the SAME object or path share
    one predictor and therefore its compiled programs.
    """

    tenant_id: str
    model: Any = None
    watch: Optional[str] = None  # CSV directory source
    out: Optional[str] = None  # CSV directory sink
    source: Any = None  # explicit StreamSource (tests / bench)
    sink: Any = None  # explicit StreamSink
    weight: float = 1.0  # fair-share weight (deficit per round)
    max_rows_per_sec: Optional[float] = None  # admission token bucket
    max_pending_batches: Optional[int] = None  # backlog cap before shed
    shed_policy: str = "oldest"  # 'oldest' | 'sample'
    quarantine_after: int = 3  # unhealthy strikes → QUARANTINED
    quarantine_cooldown_s: float = 30.0  # quarantine hold before probation
    stop_after: int = 3  # quarantine episodes → STOPPED
    row_policy: Optional[str] = None  # 'strict'|'salvage'|'permissive'
    schema_contract: Any = None
    max_batch_offsets: Optional[int] = 1
    max_batch_failures: Optional[int] = 3
    retry_policy: Optional[RetryPolicy] = None
    out_columns: Optional[List[str]] = None
    # raw-capture serving (sntc_tpu/flow): 'pcap'|'netflow' arms a
    # stateful FlowCaptureSource over the watch dir (state snapshots
    # under tenant/<id>/ckpt/flow_state); flow_options passes window
    # knobs (flow_timeout, allowed_lateness, ...) through to it
    from_capture: Optional[str] = None
    flow_options: Optional[Dict[str, Any]] = None
    # declared SLOs (r16) — the ServeController's setpoints.  None (or
    # 0, normalized below in the PR-7 style) = undeclared: the
    # controller never diagnoses this tenant as a violator on that
    # axis.  slo_p99_ms bounds the windowed p99 batch latency;
    # slo_min_rows_per_sec is the throughput floor the tenant expects
    # while it has backlog; slo_max_shed_rate bounds the fraction of
    # its offsets the shedder may drop per window before the
    # degradation ladder engages.
    slo_p99_ms: Optional[float] = None
    slo_min_rows_per_sec: Optional[float] = None
    slo_max_shed_rate: Optional[float] = None
    # durable-storage budget (r17): a per-tenant cap on the bytes this
    # tenant's checkpoint tree (tenant/<id>/) may hold — measured into
    # sntc_disk_bytes{tenant=<id>} by the daemon's StoragePlane; a
    # breach emits disk_budget_exceeded + DEGRADED health for the
    # tenant.  None/0 = unbudgeted.
    disk_budget_mb: Optional[float] = None
    # fleet placement (r19): the elastic serve fleet's coordinator
    # places tenants on workers by consistent hashing with a per-tenant
    # COST (the bounded-load capacity unit).  placement_cost defaults
    # to the DRR weight — a heavy tenant costs proportionally more of a
    # worker's capacity; pinned_worker skips hashing entirely and nails
    # the tenant to one worker id (it still migrates on that worker's
    # death).  Both are inert outside a fleet.
    placement_cost: Optional[float] = None
    pinned_worker: Optional[str] = None
    # live network front door (r20): a socket listener in front of the
    # tenant's watch dir — the watch dir becomes the ingress SPOOL and
    # the tenant replays sealed capture files (serve/ingress).  Keys:
    # listen_udp / listen_tcp (exactly one; port, 0 = ephemeral,
    # published in <watch>/ingress_stats.json), spool_mb (byte budget
    # — the backpressure/shed ladder's threshold), ring (bounded ring
    # size), seal_every (payloads per sealed file), keep_files
    # (committed-file retention), columns (TCP CSV header).
    ingress: Optional[Dict[str, Any]] = None

    def __post_init__(self):
        if not self.tenant_id or "/" in self.tenant_id:
            raise ValueError(
                f"tenant_id must be a non-empty path-safe string, got "
                f"{self.tenant_id!r}"
            )
        if self.weight <= 0:
            raise ValueError("weight must be > 0")
        if self.shed_policy not in ("oldest", "sample"):
            raise ValueError("shed_policy must be 'oldest' or 'sample'")
        if self.quarantine_after < 1 or self.stop_after < 1:
            raise ValueError(
                "quarantine_after and stop_after must be >= 1"
            )
        if self.max_batch_failures == 0:
            # the CLI documents 0 = quarantine unarmed; normalize here
            # so a per-tenant {"max_batch_failures": 0} JSON override
            # means the same thing as the daemon-level flag
            self.max_batch_failures = None
        if (
            self.max_rows_per_sec is not None
            and self.max_rows_per_sec <= 0
        ):
            raise ValueError("max_rows_per_sec must be > 0 (or None)")
        if self.row_policy is not None and self.schema_contract is None:
            # the canonical contract is the CLI's job; specs built in
            # code must be explicit about what they enforce
            raise ValueError(
                "row_policy requires a schema_contract on the spec"
            )
        # SLO fields: 0 normalizes to None (the CLI documents 0 =
        # undeclared, matching the max_batch_failures convention);
        # negative values — and a shed-rate bound over 1.0 — are typos,
        # not contracts, and must be loud
        for f in ("slo_p99_ms", "slo_min_rows_per_sec",
                  "slo_max_shed_rate", "disk_budget_mb",
                  "placement_cost"):
            v = getattr(self, f)
            if v is None:
                continue
            if v == 0:
                setattr(self, f, None)
                continue
            if v < 0:
                raise ValueError(f"{f} must be >= 0 (0/None = unset)")
        if (
            self.slo_max_shed_rate is not None
            and self.slo_max_shed_rate > 1.0
        ):
            raise ValueError(
                "slo_max_shed_rate is a fraction in (0, 1]"
            )
        if self.ingress is not None:
            unknown = sorted(set(self.ingress) - INGRESS_KEYS)
            if unknown:
                raise ValueError(
                    f"unknown ingress key(s) {unknown}; known: "
                    f"{sorted(INGRESS_KEYS)}"
                )
            has_udp = self.ingress.get("listen_udp") is not None
            has_tcp = self.ingress.get("listen_tcp") is not None
            if has_udp == has_tcp:
                raise ValueError(
                    "ingress needs exactly one of listen_udp / "
                    "listen_tcp"
                )
            if self.watch is None:
                raise ValueError(
                    "ingress requires a watch dir (the spool lands "
                    "there)"
                )
            if self.from_capture == "pcap" and has_udp:
                raise ValueError(
                    "listen_udp spools NetFlow v5; from_capture="
                    "'pcap' cannot be socket-fed"
                )

    @classmethod
    def from_dict(
        cls, d: Dict[str, Any], defaults: Optional[Dict[str, Any]] = None
    ) -> "TenantSpec":
        """Build a spec from one tenant-file entry; ``defaults`` (the
        daemon CLI's flag values) fill any field the entry omits.
        Unknown keys are an error — a typo'd quota silently defaulting
        is exactly the drift the tenant file must not allow."""
        merged = dict(defaults or {})
        merged.update({("tenant_id" if k == "id" else k): v
                       for k, v in d.items()})
        known = {f.name for f in dc_fields(cls)}
        unknown = sorted(set(merged) - known)
        if unknown:
            raise ValueError(
                f"unknown TenantSpec field(s) {unknown} for tenant "
                f"{merged.get('tenant_id')!r}; known: {sorted(known)}"
            )
        return cls(**merged)


class TenantStream:
    """One tenant's engine plus the daemon-side accounting around it:
    deficit (fair share), token-bucket allowance (rate quota), ladder
    state, strike/episode counters, and latency samples.  Constructed
    by :class:`ServeDaemon`; not for standalone use."""

    _LATENCY_KEEP = 10_000

    def __init__(self, spec: TenantSpec, query: StreamingQuery, clock):
        self.spec = spec
        self.query = query
        self.prefix = f"tenant/{spec.tenant_id}/"
        self.state = "OK"
        self._clock = clock
        self.deficit = 0.0
        rate = spec.max_rows_per_sec
        # burst = one second of quota: a tenant idle for an hour gets
        # one second's rows instantly, not an hour's
        self._burst = None if rate is None else max(rate, 1.0)
        self.allowance = self._burst
        self._last_refill = clock()
        self.strikes = 0
        self.quarantine_episodes = 0
        self.quarantined_at: Optional[float] = None
        self.probation_hold = False
        self.batches_done = 0
        self.rows_done = 0
        self.shed_total_offsets = 0
        self.latencies_ms: List[float] = []
        self.stop_reason: Optional[str] = None

    # -- quota --------------------------------------------------------------

    def refill(self, now: float) -> None:
        if self.allowance is None:
            return
        elapsed = max(0.0, now - self._last_refill)
        self._last_refill = now
        self.allowance = min(
            self._burst,
            self.allowance + elapsed * self.spec.max_rows_per_sec,
        )

    def throttled(self) -> bool:
        return self.allowance is not None and self.allowance <= 0

    def set_rate_quota(self, rate: Optional[float]) -> None:
        """Live quota resize (the ServeController's throttle knob).
        ``None`` disarms the bucket; otherwise the burst re-derives
        from the new rate and the current allowance is clamped into it
        so a tighter quota takes effect this round, not after one last
        old-size burst."""
        self.spec.max_rows_per_sec = rate
        if rate is None:
            self._burst = None
            self.allowance = None
            return
        self._burst = max(rate, 1.0)
        self.allowance = (
            self._burst if self.allowance is None
            else min(self.allowance, self._burst)
        )
        self._last_refill = self._clock()

    def charge(self, rows: int) -> None:
        if self.allowance is not None:
            self.allowance -= rows

    # -- work ---------------------------------------------------------------

    def has_work(self, latest: Optional[int] = None) -> bool:
        if self.query.in_flight_count() > 0:
            return True
        if latest is None:
            latest = self.query.source.latest_offset()
        return latest > self.query.planned_offset()

    def record_commit(self, progress: Optional[dict]) -> int:
        """Fold one committed batch's progress into tenant accounting;
        returns the rows charged against the quota."""
        self.batches_done += 1
        if not progress:
            return 0
        rows = int(progress.get("numInputRows", 0))
        self.rows_done += rows
        self.latencies_ms.append(float(progress.get("durationMs", 0.0)))
        if len(self.latencies_ms) > self._LATENCY_KEEP:
            del self.latencies_ms[: -self._LATENCY_KEEP]
        self.charge(rows)
        return rows

    # -- evidence -----------------------------------------------------------

    def latency_percentiles(self) -> Dict[str, Optional[float]]:
        if not self.latencies_ms:
            return {"p50_ms": None, "p99_ms": None}
        lat = np.asarray(self.latencies_ms, np.float64)
        return {
            "p50_ms": round(float(np.percentile(lat, 50)), 3),
            "p99_ms": round(float(np.percentile(lat, 99)), 3),
        }

    def snapshot(self) -> Dict[str, Any]:
        return {
            "tenant": self.spec.tenant_id,
            "state": self.state,
            "weight": self.spec.weight,
            "batches_done": self.batches_done,
            "rows_done": self.rows_done,
            "in_flight": self.query.in_flight_count(),
            "last_committed": self.query.last_committed(),
            "strikes": self.strikes,
            "quarantine_episodes": self.quarantine_episodes,
            "shed_total_offsets": self.shed_total_offsets,
            "allowance_rows": (
                None if self.allowance is None
                else round(self.allowance, 1)
            ),
            "stop_reason": self.stop_reason,
            **self.latency_percentiles(),
        }


class ServeDaemon:
    """N tenant streams over one shared device program cache, fairly
    scheduled, fault-isolated, drainable (module docstring has the
    contracts).  Construct with specs, then :meth:`run` (the CLI
    loop), :meth:`process_available` (drain what's there), or
    :meth:`tick` (one deterministic scheduling round — the test
    surface)."""

    def __init__(
        self,
        specs: List[TenantSpec],
        root_dir: str,
        *,
        shape_buckets: int = 0,
        pipeline_depth: int = 1,
        quantum: float = 1.0,
        health: Optional[HealthMonitor] = None,
        health_json: Optional[str] = None,
        metrics_out: Optional[str] = None,
        clock=time.monotonic,
        breaker_kwargs: Optional[Dict[str, Any]] = None,
        autotune: bool = False,
        tuning_budget=None,
        controller: bool = False,
        controller_policy=None,
        disk_budget_mb: Optional[float] = None,
        dead_letter_keep: int = 200,
        device_faults: bool = True,
        device_policy=None,
        compile_budget_s: Optional[float] = None,
        standby_root: Optional[str] = None,
        repl_barrier_every: int = 1,
    ):
        if not specs:
            raise ValueError("ServeDaemon needs at least one TenantSpec")
        ids = [s.tenant_id for s in specs]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate tenant ids: {sorted(ids)}")
        self.root_dir = root_dir
        self.shape_buckets = int(shape_buckets)
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.quantum = float(quantum)
        self.health_json = health_json
        self.dead_letter_keep = max(0, int(dead_letter_keep))
        # warm-standby disaster recovery (r23): when set, every tenant
        # gets a ReplicationPlane shipping its durable tree (+ sink
        # when the spec declares an out dir) to <standby_root>/<tid>,
        # sealing a commit barrier every repl_barrier_every commits
        # through the engine's commit_listener hook.  See
        # docs/RESILIENCE.md "Disaster recovery".
        self.standby_root = standby_root
        self.repl_barrier_every = max(1, int(repl_barrier_every))
        self._repl_planes: Dict[str, Any] = {}
        # observability (r13): when set, every scheduling round also
        # atomically republishes the registry's Prometheus text here —
        # per-tenant series (rows/batches/deficit/state/transfers) are
        # already namespaced by their ``tenant`` label
        self.metrics_out = metrics_out
        self._clock = clock
        self._breaker_kwargs = dict(breaker_kwargs or {})
        # ingest autotuning (r15): one IngestAutotuner per tenant
        # engine, all drawing from ONE TuningBudget — the shared cap on
        # extra parse threads / staged ranges / pipeline slots the
        # fleet may grow, so N tenants tuning on one box cannot each
        # claim the whole host (docs/PERFORMANCE.md "Autotuned
        # ingest").  Tuners tick at the engines' own round cadence
        # inside the daemon's scheduling rounds.
        self.autotune = bool(autotune)
        self.tuning_budget = tuning_budget
        # closed-loop SLO controller (r16): when armed, the controller
        # OWNS the per-tenant ingest tuners (one owner per knob — the
        # engines do not tick their own), steers the serving knobs
        # from the TenantSpec SLO fields, and journals every decision
        # to <root>/controller.jsonl.  See docs/RESILIENCE.md
        # "Closed-loop SLO control".
        self._controller_armed = bool(controller)
        self.controller = None
        if (self.autotune or self._controller_armed) and (
            self.tuning_budget is None
        ):
            from sntc_tpu.resilience.control import TuningBudget

            self.tuning_budget = TuningBudget.default_for(len(specs))
        # durable-storage accounting (r17): one StoragePlane over the
        # whole daemon root (global budget from the flag) plus one per
        # tenant subtree (budget from TenantSpec.disk_budget_mb) — the
        # sntc_disk_* gauges and the status()["storage"] block.  The
        # tree walks are throttled inside the planes.
        self.storage = _storage.StoragePlane(
            root_dir,
            budget_bytes=(
                int(disk_budget_mb * (1 << 20)) if disk_budget_mb
                else None
            ),
        )
        self._tenant_storage: Dict[str, _storage.StoragePlane] = {
            s.tenant_id: _storage.StoragePlane(
                self.tenant_dir(s.tenant_id),
                tenant=s.tenant_id,
                budget_bytes=(
                    int(s.disk_budget_mb * (1 << 20))
                    if s.disk_budget_mb else None
                ),
            )
            for s in specs
        }
        self._owns_health = health is None
        self.health = health or HealthMonitor(clock=clock).attach()
        # compute-plane fault domain (r18): ONE domain for the whole
        # daemon — every tenant's predictor shares the physical device,
        # so a device OOM / failed compile / lost backend degrades the
        # plane once, never once per tenant (and never strikes one).
        # See docs/RESILIENCE.md "Compute-plane fault domain".
        self.device_domain = None
        if device_faults:
            from sntc_tpu.resilience.device import (
                DeviceFaultDomain,
                DevicePolicy,
            )

            self.device_domain = DeviceFaultDomain(
                device_policy
                or DevicePolicy(compile_budget_s=compile_budget_s)
            )
        # shared program cache: one BatchPredictor per distinct model —
        # keyed by checkpoint path (str specs) or object identity —
        # handed to every tenant that declared it
        self._predictors: Dict[Any, BatchPredictor] = {}
        self._models_by_path: Dict[str, Any] = {}
        self._warm_compiles: Optional[Dict[Any, int]] = None
        self.tenants: List[TenantStream] = []
        try:
            for spec in specs:
                self.tenants.append(self._build_tenant(spec))
        except BaseException:
            # a bad spec must not leak what __init__ already set up
            # (close() can never run when __init__ raises): the health
            # observer this daemon just attached, and every
            # earlier-built tenant's registered breakers and source
            if self._owns_health:
                self.health.close()
            for t in self.tenants:
                close = getattr(t.query.source, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass
                reset_breakers(prefix=t.prefix)
            raise
        self._by_id = {t.spec.tenant_id: t for t in self.tenants}
        if self._controller_armed:
            from sntc_tpu.serve.controller import ServeController

            self.controller = ServeController.for_daemon(
                self, policy=controller_policy,
            )
        # strike counting rides the event stream: engine-emitted
        # UNHEALTHY-class events carry the tenant tag (overlap-mode
        # delivery threads emit too, hence the lock)
        self._strike_lock = threading.Lock()
        self._observer = self._on_event
        from sntc_tpu.resilience import add_event_observer

        add_event_observer(self._observer)
        self._drain = threading.Event()
        self._drain_reason: Optional[str] = None
        self.drained = False
        self._closed = False
        # the scheduler/drain mutex (r19, satellite bugfix): tick() and
        # drain() both take it, so a drain invoked from another thread
        # (a fleet coordinator, a signal-adjacent watchdog) SETTLES the
        # in-flight scheduling round before it starts tearing tenants
        # down instead of racing it.  Re-entrant: the daemon's own
        # thread draining from inside run()'s finally (or a signal
        # handler interrupting tick() on the main thread) must not
        # deadlock against itself.
        self._sched_lock = threading.RLock()
        # elastic-fleet wiring (r19): the fleet worker installs a
        # callable here; the controller's migrate/scale_out rungs post
        # requests through request_fleet().  None = not in a fleet.
        self.fleet_hook = None

    # -- construction -------------------------------------------------------

    def _resolve_model(self, spec: TenantSpec):
        if isinstance(spec.model, str):
            if spec.model not in self._models_by_path:
                from sntc_tpu.mlio import load_model

                self._models_by_path[spec.model] = load_model(spec.model)
            return spec.model, self._models_by_path[spec.model]
        if spec.model is None:
            raise ValueError(
                f"tenant {spec.tenant_id!r} has no model"
            )
        return id(spec.model), spec.model

    def predictor_for(self, spec: TenantSpec) -> BatchPredictor:
        """The SHARED predictor for this spec's pipeline: same model
        (object or path) → same predictor → same compiled bucketed /
        fused programs.  A spec handing in a ``BatchPredictor``
        directly shares by that object's identity (its own bucket
        config wins)."""
        if isinstance(spec.model, BatchPredictor):
            self._predictors.setdefault(id(spec.model), spec.model)
            return spec.model
        key, model = self._resolve_model(spec)
        pred = self._predictors.get(key)
        if pred is None:
            pred = BatchPredictor(
                model, bucket_rows=self.shape_buckets,
                device_domain=self.device_domain,
            )
            self._predictors[key] = pred
        return pred

    def device_degraded(self) -> bool:
        """True while the shared compute plane serves HOST_DEGRADED —
        the SLO controller reads this to steer knobs instead of
        escalating tenant ladders for a platform fault."""
        return (
            self.device_domain is not None
            and self.device_domain.host_degraded
        )

    def tenant_dir(self, tenant_id: str) -> str:
        return os.path.join(self.root_dir, "tenant", tenant_id)

    def _build_tenant(self, spec: TenantSpec) -> TenantStream:
        tdir = self.tenant_dir(spec.tenant_id)
        source = spec.source
        listeners = []
        if source is None and spec.ingress is not None:
            # live network front door (r20): the tenant's watch dir IS
            # the ingress spool — a listener seals socket payloads into
            # it and the tenant replays the sealed files; drain/close
            # settle the listener through the source's lifecycle hooks
            from sntc_tpu.serve import ingress as _ingress

            ing = spec.ingress
            source, listeners = _ingress.build_ingress(
                spec.watch,
                listen_udp=ing.get("listen_udp"),
                listen_tcp=ing.get("listen_tcp"),
                spool_mb=ing.get("spool_mb"),
                keep_files=ing.get("keep_files", 64),
                ring=ing.get("ring", 2048),
                seal_every=ing.get("seal_every", 30),
                seal_idle_s=ing.get("seal_idle_s", 0.25),
                columns=ing.get("columns"),
                tenant=spec.tenant_id,
                source_kwargs={
                    "parse_salvage": spec.schema_contract is not None,
                },
            )
        if source is None:
            if spec.watch is None:
                raise ValueError(
                    f"tenant {spec.tenant_id!r} needs a source or a "
                    "watch directory"
                )
            if spec.from_capture:
                from sntc_tpu.flow import FlowCaptureSource

                source = FlowCaptureSource(
                    spec.watch,
                    format=spec.from_capture,
                    state_dir=os.path.join(tdir, "ckpt", "flow_state"),
                    tenant=spec.tenant_id,
                    **(spec.flow_options or {}),
                )
            else:
                source = FileStreamSource(
                    spec.watch,
                    parse_salvage=spec.schema_contract is not None,
                )
        sink = spec.sink
        if sink is None:
            if spec.out is None:
                raise ValueError(
                    f"tenant {spec.tenant_id!r} needs a sink or an out "
                    "directory"
                )
            sink = CsvDirSink(spec.out, columns=spec.out_columns)
        prefix = f"tenant/{spec.tenant_id}/"
        breakers = {
            site: breaker_for(prefix + site, **self._breaker_kwargs)
            for site in ("sink.write", "predict.dispatch")
        }
        autotuner = None
        if self.autotune and not self._controller_armed:
            # with the SLO controller armed the CONTROLLER owns the
            # tuners (ticked per window, pipeline_depth excluded);
            # engine-owned tuners would double-steer the same knobs
            from sntc_tpu.data.autotune import IngestAutotuner

            autotuner = IngestAutotuner(
                budget=self.tuning_budget, tenant=spec.tenant_id
            )
        commit_listener = None
        if self.standby_root:
            from sntc_tpu.resilience.replicate import ReplicationPlane

            plane = ReplicationPlane(
                tdir,
                self.standby_root,
                tenant=spec.tenant_id,
                barrier_every=self.repl_barrier_every,
                sink_dir=spec.out,
            )
            self._repl_planes[spec.tenant_id] = plane
            commit_listener = plane.on_commit
        query = StreamingQuery(
            self.predictor_for(spec),
            source,
            sink,
            os.path.join(tdir, "ckpt"),
            max_batch_offsets=spec.max_batch_offsets,
            pipeline_depth=self.pipeline_depth,
            overlap_sink=self.pipeline_depth > 1,
            breakers=breakers,
            retry_policy=spec.retry_policy,
            max_batch_failures=spec.max_batch_failures,
            schema_contract=spec.schema_contract,
            row_policy=spec.row_policy,
            tenant=spec.tenant_id,
            autotuner=autotuner,
            dead_letter_keep=self.dead_letter_keep,
            commit_listener=commit_listener,
        )
        if listeners:
            from sntc_tpu.serve import ingress as _ingress

            # retention may only prune BELOW the engine's committed
            # horizon; the listeners go live only once the engine that
            # replays their spool exists
            _ingress.wire_committed_offset(source, query.committed_end)
            for l in listeners:
                l.start()
        return TenantStream(spec, query, self._clock)

    def autotune_stats(self) -> Optional[Dict[str, Any]]:
        """Per-tenant autotuner evidence + the shared budget (None when
        autotuning is unarmed) — the bench/status surface."""
        if not self.autotune:
            return None
        out: Dict[str, Any] = {
            "tenants": {
                t.spec.tenant_id: t.query.autotuner.stats()
                for t in self.tenants
                if t.query.autotuner is not None
            }
        }
        if self.tuning_budget is not None:
            out["budget"] = self.tuning_budget.snapshot()
        return out

    # -- compile-ledger evidence -------------------------------------------

    def compile_ledger(self) -> Dict[str, Dict[str, int]]:
        return {
            str(key): {
                "compile_events": p.compile_events,
                "bucket_hits": p.bucket_hits,
            }
            for key, p in self._predictors.items()
        }

    def mark_warm(self) -> None:
        """Snapshot every shared predictor's compile counter; later
        :meth:`recompiles_after_warmup` is the delta — the
        zero-cross-tenant-recompiles evidence bench config 8 journals."""
        self._warm_compiles = {
            key: p.compile_events for key, p in self._predictors.items()
        }

    def recompiles_after_warmup(self) -> Optional[int]:
        if self._warm_compiles is None:
            return None
        return sum(
            p.compile_events - self._warm_compiles.get(key, 0)
            for key, p in self._predictors.items()
        )

    # -- escalation ladder --------------------------------------------------

    def _on_event(self, record: Dict[str, Any]) -> None:
        if record.get("event") not in STRIKE_EVENTS:
            return
        tenant = record.get("tenant")
        if tenant is None:
            # breaker / retry-executor events carry no tenant field but
            # fire against the tenant's NAMESPACED site — attribute by
            # prefix so an open breaker or exhausted retry strikes too
            site = record.get("site")
            if isinstance(site, str) and site.startswith("tenant/"):
                parts = site.split("/", 2)
                tenant = parts[1] if len(parts) == 3 else None
        if tenant is None:
            return
        t = self._by_id.get(tenant)
        if t is None or t.state == "STOPPED":
            return
        with self._strike_lock:
            t.strikes += 1
        inc("sntc_tenant_strikes_total", tenant=t.spec.tenant_id)

    def _escalate(self, now: float) -> None:
        """Ladder transitions, once per tick: quarantine release after
        cooldown (probation: health reset, fresh strikes), strike
        threshold → QUARANTINED, episode threshold → STOPPED."""
        for t in self.tenants:
            if t.state == "STOPPED":
                continue
            if t.state == "QUARANTINED":
                if now - t.quarantined_at >= t.spec.quarantine_cooldown_s:
                    t.state = "OK"
                    t.quarantined_at = None
                    t.probation_hold = True  # release tick stays pure
                    with self._strike_lock:
                        t.strikes = 0
                    self.health.reset_under(
                        t.prefix, reason="quarantine released (probation)"
                    )
                    # probation means a real chance: an OPEN breaker
                    # left from the episode would refuse every call and
                    # starve the ladder of fresh evidence
                    for br in t.query.breakers.values():
                        br.reset()
                    emit_event(
                        event="tenant_released", tenant=t.spec.tenant_id,
                        episodes=t.quarantine_episodes,
                    )
                continue
            with self._strike_lock:
                strikes = t.strikes
            if strikes >= t.spec.quarantine_after:
                t.quarantine_episodes += 1
                if t.quarantine_episodes >= t.spec.stop_after:
                    self._stop_tenant(
                        t,
                        reason=f"{t.quarantine_episodes} quarantine "
                        "episodes",
                    )
                    continue
                t.state = "QUARANTINED"
                t.quarantined_at = now
                with self._strike_lock:
                    t.strikes = 0
                emit_event(
                    event="tenant_quarantined", tenant=t.spec.tenant_id,
                    strikes=strikes, episode=t.quarantine_episodes,
                    cooldown_s=t.spec.quarantine_cooldown_s,
                )

    def _stop_tenant(self, t: TenantStream, reason: str) -> None:
        """Terminal eviction: the tenant's engine stops, its breakers
        leave the process registry, its WAL keeps whatever a restart
        would need.  The daemon — and every other tenant — keeps
        serving."""
        t.state = "STOPPED"
        t.stop_reason = reason
        try:
            t.query.stop()
        except Exception as e:  # a wedged engine must not stop the stop
            emit_event(
                event="tenant_error", tenant=t.spec.tenant_id,
                error=repr(e), during="stop",
            )
        close = getattr(t.query.source, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass
        reset_breakers(prefix=t.prefix)
        emit_event(
            event="tenant_stopped", tenant=t.spec.tenant_id,
            reason=reason,
        )

    def tenant_state(self, tenant_id: str) -> str:
        return self._by_id[tenant_id].state

    def tenant_health(self, tenant_id: str) -> HealthState:
        """Worst health among the tenant's OWN namespaced components."""
        return self.health.worst_under(self._by_id[tenant_id].prefix)

    # -- the scheduler ------------------------------------------------------

    def tick(self) -> int:
        """One deficit-round-robin scheduling round; returns batches
        committed across all tenants.  Order per round: ladder
        transitions, quota refills, per-tenant shed decisions, then
        credit every runnable tenant ``weight × quantum`` deficit and
        drain the rotation — each committed micro-batch costs one
        deficit and charges its rows to the tenant's bucket, so a
        heavy tenant exhausts its credit (or allowance) and the
        rotation moves on.  An engine error strikes the tenant and the
        round continues; the daemon loop never dies for one tenant."""
        now = self._clock()
        inc("sntc_daemon_ticks_total")
        committed_total = 0
        with self._sched_lock, span("daemon.tick"):
            self._escalate(now)
            runnable: List[TenantStream] = []
            for t in self.tenants:
                if t.state in ("STOPPED", "QUARANTINED"):
                    continue
                if t.probation_hold:
                    # the tick that released this tenant does not also
                    # serve it: release is observable (state OK, health
                    # reset) before the first probation batch can
                    # re-dirty either one
                    t.probation_hold = False
                    continue
                t.refill(now)
                try:
                    latest = t.query.source.latest_offset()
                except Exception as e:
                    self._strike(t, e, during="latest_offset")
                    continue
                if t.spec.max_pending_batches is not None:
                    try:
                        shed = t.query.shed_backlog(
                            t.spec.max_pending_batches,
                            policy=t.spec.shed_policy,
                            latest=latest,
                        )
                    except Exception as e:
                        self._strike(t, e, during="shed")
                        shed = None
                    if shed is not None:
                        t.shed_total_offsets += shed.get(
                            "offsets_shed", 0
                        )
                if not t.has_work(latest):
                    t.deficit = 0.0  # DRR: idle queues keep no credit
                    if t.state == "THROTTLED":
                        t.state = "OK"
                    continue
                if t.throttled():
                    t.state = "THROTTLED"
                    continue
                if t.state == "THROTTLED":
                    t.state = "OK"
                runnable.append(t)
            for t in runnable:
                t.deficit += t.spec.weight * self.quantum
            for t in runnable:
                committed_total += self._drain_deficit(t)
            self._last_runnable = len(runnable)
            # scheduler state on the metrics plane, once per round: the
            # DRR deficits and ladder states every tenant ended with
            for t in self.tenants:
                set_gauge(
                    "sntc_tenant_deficit", t.deficit,
                    tenant=t.spec.tenant_id,
                )
                set_gauge(
                    "sntc_tenant_state", TENANT_STATES.index(t.state),
                    tenant=t.spec.tenant_id,
                )
            if self.controller is not None:
                # closed-loop SLO control, once per scheduling round —
                # degrade-never-kill exactly like the lifecycle and
                # autotune ticks: a controller bug must not stop
                # serving
                try:
                    self.controller.on_tick()
                except Exception as e:
                    emit_event(
                        event="controller_error", error=repr(e)
                    )
        # disk accounting + budget verdicts once per round (the planes
        # throttle the actual tree walks): a tenant over its declared
        # byte budget gets a disk_budget_exceeded event → DEGRADED
        # health under its own namespace, never a neighbor's
        self.storage.check_budget()
        for plane in self._tenant_storage.values():
            plane.check_budget()
        if self.health_json:
            _atomic_json(self.health_json, self.status())
        if self.metrics_out:
            registry().write_prometheus(self.metrics_out)
        return committed_total

    def _drain_deficit(self, t: TenantStream) -> int:
        """Run one tenant's engine while it has deficit, work, and
        allowance; returns batches committed."""
        committed = 0
        while (
            t.deficit >= 1.0
            and t.state not in ("STOPPED", "QUARANTINED")
        ):
            before = t.query.last_committed()
            try:
                t.query._run_one_batch()
            except Exception as e:
                self._strike(t, e, during="run_one_batch")
                t.deficit = min(
                    t.deficit, t.spec.weight * self.quantum
                )
                break
            delta = t.query.last_committed() - before
            if delta == 0:
                # deferred (breaker open / retry round) or idle: credit
                # a queue could not spend does not bank — classic DRR.
                # Without the cap, ~30 deferring ticks bank ~30 deficit
                # and the recovery tick drains them back-to-back ahead
                # of every neighbor in the rotation (a latency spike in
                # exactly the noisy-neighbor scenario fairness is for).
                t.deficit = min(
                    t.deficit, t.spec.weight * self.quantum
                )
                break
            t.deficit -= delta
            committed += delta
            # charge each committed batch's rows; recentProgress holds
            # them newest-last in commit order
            for progress in t.query.recentProgress[-delta:]:
                t.record_commit(progress)
            if t.throttled():
                t.state = "THROTTLED"
                break
        return committed

    def strike_tenant(self, tenant_id: str, reason: str) -> None:
        """One ladder strike issued by the SLO controller (the top of
        its degradation ladder: throttle → shed → escalate).  Counts
        exactly like an event-stream strike; the existing
        quarantine/stop thresholds own what happens next."""
        t = self._by_id[tenant_id]
        if t.state == "STOPPED":
            return
        with self._strike_lock:
            t.strikes += 1
        inc("sntc_tenant_strikes_total", tenant=t.spec.tenant_id)
        emit_event(
            event="controller_strike", tenant=t.spec.tenant_id,
            reason=reason,
        )

    def _strike(self, t: TenantStream, exc: Exception, during: str) -> None:
        """An engine error that surfaced to the scheduler (quarantine
        unarmed, or infrastructure failure): evidence against the
        tenant, never against the daemon."""
        with self._strike_lock:
            t.strikes += 1
        inc("sntc_tenant_strikes_total", tenant=t.spec.tenant_id)
        emit_event(
            event="tenant_error", tenant=t.spec.tenant_id,
            error=repr(exc), during=during,
        )

    # -- loop / drain -------------------------------------------------------

    def has_work(self) -> bool:
        return any(
            t.state not in ("STOPPED", "QUARANTINED") and t.has_work()
            for t in self.tenants
        )

    def process_available(self, max_rounds: int = 1_000_000) -> int:
        """Deterministically drain what every schedulable tenant has
        (the test/step API).  A zero-commit round with runnable work is
        a RETRY round (a tenant deferring toward its quarantine
        threshold), tolerated up to the bounded stall budget the
        engine's own ``drain()`` uses; a round with nothing runnable
        ends the call — a throttled tenant's backlog stays for later
        (time, not rounds, refills its bucket), a quarantined tenant's
        for its probation."""
        total = 0
        stalled = 0
        max_stalled = max(
            ((t.spec.max_batch_failures or 1) + 1) for t in self.tenants
        ) * len(self.tenants)
        for _ in range(max_rounds):
            delta = self.tick()
            total += delta
            if delta:
                stalled = 0
                continue
            if getattr(self, "_last_runnable", 0) == 0:
                break
            stalled += 1
            if stalled >= max_stalled:
                break
        return total

    def request_drain(self, reason: str = "request_drain") -> None:
        if not self._drain.is_set():
            self._drain_reason = reason
            self._drain.set()

    # -- dynamic membership (r19: the elastic serve fleet) ------------------

    def add_tenant(self, spec: TenantSpec) -> TenantStream:
        """Admit one tenant into the RUNNING daemon (the fleet worker's
        assignment-apply path): build its engine against the shared
        program cache, register its storage plane, and — when the SLO
        controller is armed — attach its knobs.  Serialized against the
        scheduler, so the tenant is either absent from a round or fully
        present in it."""
        with self._sched_lock:
            if spec.tenant_id in self._by_id:
                raise ValueError(
                    f"tenant {spec.tenant_id!r} already served"
                )
            t = self._build_tenant(spec)
            self.tenants.append(t)
            self._by_id[spec.tenant_id] = t
            self._tenant_storage[spec.tenant_id] = _storage.StoragePlane(
                self.tenant_dir(spec.tenant_id),
                tenant=spec.tenant_id,
                budget_bytes=(
                    int(spec.disk_budget_mb * (1 << 20))
                    if spec.disk_budget_mb else None
                ),
            )
            if self.controller is not None:
                try:
                    self.controller.attach_tenant(t)
                except Exception as e:  # degrade-never-kill
                    emit_event(
                        event="controller_error", error=repr(e)
                    )
            emit_event(
                event="tenant_added", tenant=spec.tenant_id,
                tenants=len(self.tenants),
            )
            return t

    def remove_tenant(
        self, tenant_id: str, *, drain: bool = True,
        reason: str = "remove_tenant",
    ) -> Dict[str, Any]:
        """Evict one tenant from the RUNNING daemon (the migration
        source path): settle it through the same bounded drain +
        marker + stop recipe the whole-daemon drain uses (``drain=False``
        skips the settle for an already-stopped engine), evict its
        breakers, and forget it.  Its on-disk tree is untouched — the
        caller (the fleet coordinator) owns shipping or deleting it.
        Returns a summary the coordinator journals."""
        with self._sched_lock:
            t = self._by_id.get(tenant_id)
            if t is None:
                raise KeyError(f"no tenant {tenant_id!r}")
            committed = 0
            was_mid_batch = (
                t.state != "STOPPED" and t.query.in_flight_count() > 0
            )
            if drain and t.state != "STOPPED":
                committed = self._settle_tenant(t, reason, was_mid_batch)
            else:
                try:
                    t.query.stop()
                except Exception:
                    pass
            close = getattr(t.query.source, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
            # the engine is stopped for good: flip the stream state
            # BEFORE detaching, so anything still holding the stream
            # sees a non-controllable tenant, and detach the armed
            # controller (its target list must not keep sampling a
            # ghost — nor post fleet requests for a tenant another
            # worker now owns)
            t.state = "STOPPED"
            if self.controller is not None:
                try:
                    self.controller.detach_tenant(tenant_id)
                except Exception as e:  # degrade-never-kill
                    emit_event(
                        event="controller_error", error=repr(e)
                    )
            reset_breakers(prefix=t.prefix)
            self.tenants.remove(t)
            del self._by_id[tenant_id]
            self._tenant_storage.pop(tenant_id, None)
            emit_event(
                event="tenant_removed", tenant=tenant_id,
                reason=reason, tenants=len(self.tenants),
            )
            return {
                "tenant": tenant_id,
                "reason": reason,
                "batches_committed_at_remove": committed,
                "last_committed": t.query.last_committed(),
                "was_mid_batch": was_mid_batch,
                "rows_done": t.rows_done,
            }

    def request_fleet(
        self, action: str, tenant_id: str, reason: str = ""
    ) -> bool:
        """Post one fleet request (``migrate`` / ``scale_out``) through
        the installed fleet hook — the controller's fleet rungs land
        here.  Returns False (and emits, never raises) when the daemon
        is not in a fleet or the hook fails: a fleet request is advice
        to the coordinator, not a local state change."""
        if self.fleet_hook is None:
            return False
        try:
            self.fleet_hook(action, tenant_id, reason)
        except Exception as e:
            emit_event(
                event="fleet_request_error", tenant=tenant_id,
                action=action, error=repr(e),
            )
            return False
        emit_event(
            event="fleet_request", tenant=tenant_id, action=action,
            reason=reason,
        )
        return True

    @property
    def drain_requested(self) -> bool:
        return self._drain.is_set()

    def install_signal_handlers(self) -> bool:
        try:
            signal.signal(
                signal.SIGTERM,
                lambda signum, frame: self.request_drain("SIGTERM"),
            )
            return True
        except ValueError:  # not the main thread
            return False

    def _settle_tenant(
        self, t: TenantStream, reason: Optional[str],
        was_mid_batch: bool,
    ) -> int:
        """Settle ONE tenant: bounded engine drain (anything still
        deferring stays in its WAL for a restart, the crash contract),
        atomic per-tenant drain marker, engine stop.  Shared by the
        whole-daemon :meth:`drain` and the fleet's per-tenant
        :meth:`remove_tenant`; returns batches committed."""
        drain_ingress = getattr(t.query.source, "drain_ingress", None)
        if drain_ingress is not None:
            # settle the socket front door FIRST: intake stops and the
            # ring tail seals DURABLY before the engine stops, so
            # nothing a sender was promised (the sealed-file ack) can
            # die in memory — a restart replays the tail from the spool
            try:
                drain_ingress()
            except Exception as e:
                emit_event(
                    event="tenant_error", tenant=t.spec.tenant_id,
                    error=repr(e), during="drain_ingress",
                )
        try:
            done = t.query.drain()
        except Exception as e:
            emit_event(
                event="tenant_error", tenant=t.spec.tenant_id,
                error=repr(e), during="drain",
            )
            done = 0
        for progress in t.query.recentProgress[-done:] if done else []:
            t.record_commit(progress)
        _atomic_json(
            os.path.join(
                self.tenant_dir(t.spec.tenant_id), "drain_marker.json"
            ),
            {
                "ts": time.time(),
                "tenant": t.spec.tenant_id,
                "reason": reason,
                "last_committed": t.query.last_committed(),
                "end_offset": t.query.committed_end(),
                "in_flight_left": t.query.in_flight_count(),
                # the tenant had un-committed in-flight batches when
                # the drain was requested (they were settled — or
                # WAL-parked — before this marker was written)
                "was_mid_batch": was_mid_batch,
                # final controller-steered knob state: a restart
                # (cold defaults) reads this to log the delta
                "controller_knobs": (
                    self.controller.knob_values_for(
                        t.spec.tenant_id
                    )
                    if self.controller is not None else None
                ),
            },
        )
        try:
            t.query.stop()
        except Exception as e:
            emit_event(
                event="tenant_error", tenant=t.spec.tenant_id,
                error=repr(e), during="stop",
            )
        return done

    def drain(self) -> int:
        """Settle every live tenant: finish + commit its in-flight
        batches (the engine's bounded drain — anything still deferring
        stays in its WAL for a restart, the crash contract), write one
        atomic marker per tenant and one for the daemon, stop the
        engines.  Idempotent; returns batches committed during the
        drain.

        Takes the scheduler mutex, so a drain requested from another
        thread mid-:meth:`tick` waits for the in-flight scheduling
        round to settle instead of racing it (r19 bugfix) — and the
        markers record which tenants were MID-BATCH at that moment,
        the evidence a coordinator-initiated drain needs to decide
        whether a migration may ship immediately or must wait for a
        WAL-replay restart."""
        with self._sched_lock:
            if self.drained:
                return 0
            # capture the mid-batch set BEFORE settling: after
            # t.query.drain() the in-flight evidence is gone
            mid_batch = [
                t.spec.tenant_id for t in self.tenants
                if t.state != "STOPPED" and t.query.in_flight_count() > 0
            ]
            committed = 0
            for t in self.tenants:
                if t.state == "STOPPED":
                    continue
                committed += self._settle_tenant(
                    t, self._drain_reason,
                    t.spec.tenant_id in mid_batch,
                )
            # final ship + barrier so a drain with barrier_every > 1
            # never strands a replicated-but-unacked tail
            for plane in self._repl_planes.values():
                plane.close()
            self.drained = True
            _atomic_json(
                os.path.join(self.root_dir, DAEMON_DRAIN_MARKER),
                {
                    "ts": time.time(),
                    "reason": self._drain_reason,
                    "pid": os.getpid(),
                    "tenants": {
                        t.spec.tenant_id: t.state for t in self.tenants
                    },
                    "mid_batch_tenants": mid_batch,
                    "batches_committed_at_drain": committed,
                    "controller_knobs": (
                        self.controller.knob_values()
                        if self.controller is not None else None
                    ),
                },
            )
            emit_event(
                event="daemon_drained", reason=self._drain_reason,
                tenants=len(self.tenants), committed=committed,
            )
            return committed

    def run(
        self,
        poll_interval: float = 1.0,
        max_batches: Optional[int] = None,
    ) -> Dict[str, Any]:
        """The supervised foreground loop: tick until ``max_batches``
        total commits or a drain request; idle ticks wait
        ``poll_interval`` (interruptibly).  Always drains on the way
        out and returns the final :meth:`status`."""
        done = 0
        try:
            while not self._drain.is_set():
                delta = self.tick()
                done += delta
                if max_batches is not None and done >= max_batches:
                    break
                if delta == 0:
                    if self._warm_compiles is None:
                        # first idle round = the initial backlog is
                        # served and every live signature compiled:
                        # everything after this is the measured cache
                        self.mark_warm()
                    self._drain.wait(poll_interval)
        finally:
            self.drain()
            if self.health_json:
                _atomic_json(self.health_json, self.status())
        return self.status()

    # -- status / teardown --------------------------------------------------

    def status(self) -> Dict[str, Any]:
        from sntc_tpu.resilience import breakers_snapshot

        tenant_rows = {
            t.spec.tenant_id: t.snapshot() for t in self.tenants
        }
        return {
            "tenants": tenant_rows,
            "aggregate": {
                "batches_done": sum(
                    t.batches_done for t in self.tenants
                ),
                "rows_done": sum(t.rows_done for t in self.tenants),
                "states": {
                    s: sum(1 for t in self.tenants if t.state == s)
                    for s in TENANT_STATES
                },
            },
            "compile_ledger": self.compile_ledger(),
            "recompiles_after_warmup": self.recompiles_after_warmup(),
            # compute-plane fault domain (r18): the shared device's
            # serving state + response-ladder evidence (one block —
            # tenants share the physical device)
            "device": (
                self.device_domain.stats()
                if self.device_domain is not None else None
            ),
            "autotune": self.autotune_stats(),
            "slo": (
                self.controller.slo_status()
                if self.controller is not None else None
            ),
            "controller": (
                self.controller.stats()
                if self.controller is not None else None
            ),
            "health": self.health.snapshot(),
            "breakers": {
                site: snap
                for site, snap in breakers_snapshot().items()
                if site.startswith("tenant/")
            },
            "events_dropped": events_dropped(),
            "events_dropped_by_tenant": events_dropped(by_tenant=True),
            "drain_requested": self.drain_requested,
            "drained": self.drained,
            # durable-storage lifecycle (r17): whole-root accounting +
            # per-tenant subtree accounting/budgets, plus each engine's
            # WAL/journal bound counters
            "storage": {
                "global": self.storage.status(),
                "tenants": {
                    tid: plane.status()
                    for tid, plane in self._tenant_storage.items()
                },
                "engines": {
                    t.spec.tenant_id: t.query.storage_stats()
                    for t in self.tenants
                },
            },
        }

    def close(self) -> None:
        """Daemon teardown: detach the strike observer and the owned
        health monitor from the process event stream, stop engines that
        are still live, close sources, and evict every tenant's
        breakers from the process registry.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        from sntc_tpu.resilience import remove_event_observer

        remove_event_observer(self._observer)
        if self._owns_health:
            self.health.close()
        for plane in self._repl_planes.values():
            try:
                plane.close()
            except Exception:
                pass
        for t in self.tenants:
            if t.state != "STOPPED":
                try:
                    t.query.stop()
                except Exception:
                    pass
                close = getattr(t.query.source, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass
            reset_breakers(prefix=t.prefix)
