"""Serving-time stage fusion — fold StandardScaler into model weights.

The serving hot path (config 5 [B:11]) runs VectorAssembler → scaler →
classifier per micro-batch.  The scaler is an affine map, so for linear
heads and MLP first layers it folds EXACTLY into the weights:

    x' = (x - μ)·f        (f = 1/σ, 0 for constant features)
    x'W + b  =  x(f⊙W) + (b - (μ⊙f)W)

``compile_serving`` rewrites a fitted PipelineModel, merging each
(StandardScalerModel, LogisticRegressionModel | MLP model) pair into one
stage that consumes the scaler's input column — one fewer full pass over
every batch, and the whole predict stays in a single jit program.  This
is the kind of cross-stage fusion Spark's whole-stage codegen does for
relational operators (SURVEY.md §2.6), applied to the ML pipeline.
"""

from __future__ import annotations

import numpy as np

from sntc_tpu.core.base import PipelineModel, Transformer
from sntc_tpu.feature.standard_scaler import StandardScalerModel
from sntc_tpu.models.logistic_regression import LogisticRegressionModel
from sntc_tpu.models.mlp import (
    MultilayerPerceptronClassificationModel,
    _layer_sizes,
)


def _fold_into_lr(
    scaler: StandardScalerModel, model: LogisticRegressionModel
) -> LogisticRegressionModel:
    mu, f = scaler.affine()
    W = model.coefficientMatrix.astype(np.float64)  # [K, D]
    b = model.interceptVector.astype(np.float64)
    W2 = W * f[None, :]
    b2 = b - W2 @ mu
    folded = LogisticRegressionModel(
        coefficient_matrix=W2.astype(np.float32),
        intercepts=b2.astype(np.float32),
        is_binomial=model.is_binomial,
    )
    folded.setParams(**model.paramValues())
    folded.set("featuresCol", scaler.getInputCol())
    return folded


def _fold_into_mlp(
    scaler: StandardScalerModel, model: MultilayerPerceptronClassificationModel
) -> MultilayerPerceptronClassificationModel:
    mu, f = scaler.affine()
    layers = tuple(int(v) for v in model.getLayers())
    d_in, d_h = _layer_sizes(layers)[0]
    theta = model.weights.astype(np.float64).copy()
    W1 = theta[: d_in * d_h].reshape(d_in, d_h)
    b1 = theta[d_in * d_h : d_in * d_h + d_h]
    W1_new = f[:, None] * W1
    b1_new = b1 - (mu * f) @ W1
    theta[: d_in * d_h] = W1_new.reshape(-1)
    theta[d_in * d_h : d_in * d_h + d_h] = b1_new
    folded = MultilayerPerceptronClassificationModel(
        weights=theta.astype(np.float32), layers=list(layers)
    )
    folded.setParams(**{
        k: v for k, v in model.paramValues().items() if k != "layers"
    })
    folded.set("featuresCol", scaler.getInputCol())
    return folded


_FOLDABLE = {
    LogisticRegressionModel: _fold_into_lr,
    MultilayerPerceptronClassificationModel: _fold_into_mlp,
}


def _consumes(stage, col: str) -> bool:
    # total, not heuristic: Transformer.input_columns() covers the standard
    # input params and is overridable by stages with nonstandard ones
    return col in stage.input_columns()


def compile_serving(pipeline: PipelineModel) -> PipelineModel:
    """Return an equivalent PipelineModel with scaler→classifier pairs
    fused (non-matching stage patterns pass through untouched).

    The scaler stage is dropped only when the classifier is its SOLE
    consumer — if any later stage also reads the scaled column, the pair
    is left unfused so that column still exists at transform time.
    """
    stages = list(pipeline.getStages())
    out = []
    i = 0
    while i < len(stages):
        s = stages[i]
        nxt = stages[i + 1] if i + 1 < len(stages) else None
        fold = _FOLDABLE.get(type(nxt)) if nxt is not None else None
        if (
            isinstance(s, StandardScalerModel)
            and fold is not None
            and nxt.getFeaturesCol() == s.getOutputCol()
            and not any(
                _consumes(later, s.getOutputCol()) for later in stages[i + 2:]
            )
        ):
            out.append(fold(s, nxt))
            i += 2
        else:
            out.append(s)
            i += 1
    return PipelineModel(stages=out)
