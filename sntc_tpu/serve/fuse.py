"""Serving-time pipeline compilation — moved to :mod:`sntc_tpu.fuse`.

This module was the r5 pairwise scaler→classifier fold.  r9 promoted it
into a whole-pipeline fusion compiler (``sntc_tpu/fuse/``): the scaler
fold is now rewrite rule 1 of that pass (``fuse.rules.fold_scalers``),
and ``compile_serving`` — kept here as the stable import path — aliases
:func:`sntc_tpu.fuse.compile_pipeline`, which additionally partitions
the pipeline into maximal fusible segments and jit-compiles each into
one device program (see ``docs/PERFORMANCE.md``, "Whole-pipeline
fusion").
"""

from __future__ import annotations

from sntc_tpu.fuse import compile_pipeline, compile_serving
from sntc_tpu.fuse.rules import _fold_into_lr, _fold_into_mlp, fold_scalers

__all__ = [
    "compile_pipeline",
    "compile_serving",
    "fold_scalers",
]
