"""Live NetFlow ingest for streaming inference [B:11].

Design: UDP datagrams are not replayable, so exactly-once streaming over
live NetFlow splits into (1) ``capture_udp`` — a collector that write-
ahead-logs raw datagrams to capture files, and (2) ``NetFlowDirSource`` —
a replayable micro-batch source over those files (offset = file count),
decoded by the native C++ parser (sntc_tpu/native) and lifted into the
CICIDS2017 flow schema for the trained pipeline.  This mirrors Spark's
reliable-receiver pattern: persist first, then process from the log.
"""

from __future__ import annotations

import glob
import os
import socket
import warnings
from typing import List, Optional

from sntc_tpu.core.frame import Frame
from sntc_tpu.native import netflow_to_flow_frame, parse_stream
from sntc_tpu.obs.metrics import inc
from sntc_tpu.resilience import fault_data
from sntc_tpu.serve.streaming import DirStreamSource


class _CaptureDirSource(DirStreamSource):
    """Capture-file directory source: one decoded Frame per file.
    Subclasses implement ``_decode_file(bytes) -> Frame``.

    Inherits the full :class:`DirStreamSource` pipeline surface —
    per-tick listing cache, parallel per-file decodes
    (``read_workers``), background staging (``prefetch_batches``), the
    source-graph stage meters, and the live ``set_read_workers`` /
    ``set_prefetch_batches`` resize surface the ingest autotuner
    drives; decode is CPU-bound Python for pcap, so staging width is
    the lever that matters there.

    Raw capture bytes pass through the ``source.parse`` fault site
    (``fault_data``) before decode, so the corrupt-input chaos kinds
    (``corrupt_bytes``/``truncate``/``ragged``) exercise the binary
    parsers' bounds-checked salvage exactly like the CSV path's."""

    def _decode_file(self, data: bytes) -> Frame:
        raise NotImplementedError

    def _load_file(self, path: str) -> Frame:
        with open(path, "rb") as f:
            data = f.read()
        labels = {} if self.tenant is None else {"tenant": self.tenant}
        inc("sntc_ingest_bytes_read_total", len(data), **labels)
        return self._decode_file(fault_data("source.parse", data))


def decode_pcap_packets(data: bytes):
    """``parse_pcap`` with THE capture-file serving policy, shared by
    every pcap-serving source (:class:`PcapDirSource`, the flow
    engine's ``FlowCaptureSource``): a short header is a
    partially-written capture (external writer race) — FAILING the
    batch is the lossless choice, the intent stays uncommitted in the
    WAL and the engine replays it next poll when the file is complete
    (writers should rename into place atomically, as ``capture_udp``
    does); ≥24 bytes with a bad magic or unsupported linktype will
    never become readable — retrying would wedge the stream forever,
    so skip it (0 packets) and warn, like Spark's badRecordsPath.
    Returns the ``[n, PCAP_FIELDS]`` packet matrix."""
    import numpy as np

    from sntc_tpu.native import PCAP_FIELDS, parse_pcap

    pkts = parse_pcap(data)
    if pkts is None:
        if len(data) < 24:
            raise ValueError(
                "truncated pcap capture (partial write? writers must "
                "rename into place atomically); batch will be retried"
            )
        warnings.warn(
            "skipping unreadable capture file (bad magic or "
            "unsupported linktype; only Ethernet/raw-IP are decoded)"
        )
        return np.zeros((0, PCAP_FIELDS), np.float64)
    return pkts


class NetFlowDirSource(_CaptureDirSource):
    """Directory of NetFlow v5 capture files (``*.nf5``)."""

    def __init__(self, path: str, pattern: str = "*.nf5", **kwargs):
        super().__init__(path, pattern, **kwargs)

    def _decode_file(self, data: bytes) -> Frame:
        return netflow_to_flow_frame(parse_stream(data))


def _capture_index(path: str) -> int:
    """Sequence index embedded in a capture file name
    (``capture_000042.nf5`` -> 42); non-conforming names count as -1 so
    a foreign file never inflates the resume point."""
    import re

    m = re.search(r"(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else -1


def capture_udp(
    port: int,
    out_dir: str,
    max_datagrams: int,
    timeout_s: float = 5.0,
    host: str = "127.0.0.1",
    datagrams_per_file: int = 100,
    sock: Optional[socket.socket] = None,
) -> int:
    """Collect NetFlow datagrams from UDP into capture files (the WAL the
    replayable source reads).  Returns the number of datagrams captured.

    Deprecated-compat path: :class:`sntc_tpu.serve.ingress
    .UdpIngressListener` is the supervised front door (bounded ring,
    counted shed, retention, drain); this blocking helper remains for
    scripts but now shares its durability discipline — capture files
    publish through the fsynced atomic rename (file + containing dir),
    and the sequence index resumes from max-existing-index + 1, so a
    retention-pruned spool never reuses an index and silently
    overwrites a live capture."""
    from sntc_tpu.resilience.storage import atomic_write_bytes

    os.makedirs(out_dir, exist_ok=True)
    own_sock = sock is None
    if own_sock:
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind((host, port))
    sock.settimeout(timeout_s)
    captured = 0
    buf: List[bytes] = []
    existing = glob.glob(os.path.join(out_dir, "*.nf5"))
    file_idx = max(
        (_capture_index(p) for p in existing), default=-1
    ) + 1

    def flush():
        nonlocal file_idx, buf
        if buf:
            path = os.path.join(out_dir, f"capture_{file_idx:06d}.nf5")
            atomic_write_bytes(
                path, b"".join(buf), site="ingress.spool"
            )
            file_idx += 1
            buf = []

    try:
        while captured < max_datagrams:
            try:
                data, _ = sock.recvfrom(65_535)
            except socket.timeout:
                break
            buf.append(data)
            captured += 1
            if len(buf) >= datagrams_per_file:
                flush()
    finally:
        flush()
        if own_sock:
            sock.close()
    return captured


class PcapDirSource(_CaptureDirSource):
    """Directory of pcap capture files — the pcap half of [B:11]'s
    "NetFlow/pcap micro-batches".  Each capture file's packets are
    metered into CICIDS2017-schema flows (sntc_tpu/native/pcap.py)."""

    def __init__(
        self,
        path: str,
        pattern: str = "*.pcap",
        flow_timeout: float = 120.0,
        activity_timeout: float = 5.0,
        **kwargs,
    ):
        super().__init__(path, pattern, **kwargs)
        self.flow_timeout = flow_timeout
        self.activity_timeout = activity_timeout

    def _decode_file(self, data: bytes) -> Frame:
        from sntc_tpu.native import packets_to_flow_frame

        return packets_to_flow_frame(
            decode_pcap_packets(data),
            flow_timeout=self.flow_timeout,
            activity_timeout=self.activity_timeout,
        )
