"""Micro-batch streaming inference — the Structured-Streaming analog [B:11].

Behavioral spec: SURVEY.md §3.5/§5.4 mechanism 3 (upstream
``MicroBatchExecution`` + ``OffsetSeqLog``/``CommitLog`` [U]): the engine
loop resolves the source's latest offset, write-ahead-logs the intended
batch range (``offsets/<id>.json``), runs the batch through the model,
hands it to the sink, then commits (``commits/<id>.json``).  On restart
with the same checkpoint dir, an uncommitted intent is REPLAYED with its
logged range, giving exactly-once batches w.r.t. the offset log — Spark's
recovery contract.

Sources implement ``latest_offset()`` and ``get_batch(start, end)`` over a
monotonic integer offset (file count / row count — Spark's file-source
model).  ``process_available()`` steps the engine deterministically (the
``StreamTest`` harness analog, SURVEY.md §4).
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import List, Optional

import numpy as np

from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.data.ingest import load_csv
from sntc_tpu.serve.transform import BatchPredictor


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------


class StreamSource:
    def latest_offset(self) -> int:
        raise NotImplementedError

    def get_batch(self, start: int, end: int) -> Frame:
        raise NotImplementedError


class DirStreamSource(StreamSource):
    """Shared machinery for directory-watching sources: offset = count of
    files in sorted order (the ``readStream`` file-source model: new files
    are new data).  Subclasses implement ``_load_file(path) -> Frame``."""

    def __init__(self, path: str, pattern: str):
        self.path = path
        self.pattern = pattern

    def _files(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self.path, self.pattern)))

    def latest_offset(self) -> int:
        return len(self._files())

    def _load_file(self, path: str) -> Frame:
        raise NotImplementedError

    def get_batch(self, start: int, end: int) -> Frame:
        files = self._files()[start:end]
        if not files:
            raise ValueError(f"empty batch range [{start}, {end})")
        if len(files) == 1:  # common micro-batch case: skip the concat copy
            return self._load_file(files[0])
        return Frame.concat_all([self._load_file(p) for p in files])


class FileStreamSource(DirStreamSource):
    """Directory of flow CSVs."""

    def __init__(self, path: str, pattern: str = "*.csv"):
        super().__init__(path, pattern)

    def _load_file(self, path: str) -> Frame:
        return load_csv(path)


class MemorySource(StreamSource):
    """In-memory list of Frames — the ``MemoryStream`` test analog."""

    def __init__(self, frames: Optional[List[Frame]] = None):
        self._frames: List[Frame] = list(frames or [])

    def add(self, frame: Frame) -> None:
        self._frames.append(frame)

    def latest_offset(self) -> int:
        return len(self._frames)

    def get_batch(self, start: int, end: int) -> Frame:
        if end - start == 1:  # skip the concat copy for 1-frame batches
            return self._frames[start]
        return Frame.concat_all(self._frames[start:end])


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------


class StreamSink:
    def add_batch(self, batch_id: int, frame: Frame) -> None:
        raise NotImplementedError


class MemorySink(StreamSink):
    def __init__(self):
        self.batches: List[tuple] = []

    def add_batch(self, batch_id: int, frame: Frame) -> None:
        self.batches.append((batch_id, frame))

    @property
    def frames(self) -> List[Frame]:
        return [f for _, f in self.batches]


class CsvDirSink(StreamSink):
    """One CSV per batch (append output mode)."""

    def __init__(self, path: str, columns: Optional[List[str]] = None):
        self.path = path
        self.columns = columns
        os.makedirs(path, exist_ok=True)

    def add_batch(self, batch_id: int, frame: Frame) -> None:
        import pyarrow.csv as pacsv

        cols = self.columns or [
            c for c in frame.columns if frame[c].ndim == 1
        ]
        pacsv.write_csv(
            frame.select(cols).to_arrow(),
            os.path.join(self.path, f"batch_{batch_id:06d}.csv"),
        )


class ConsoleSink(StreamSink):
    def add_batch(self, batch_id: int, frame: Frame) -> None:
        print(f"[batch {batch_id}] {frame}")


# ---------------------------------------------------------------------------
# the micro-batch engine
# ---------------------------------------------------------------------------


class StreamingQuery:
    """Micro-batch inference engine (SURVEY.md §3.5, §5.4 mechanism 3).

    **Single writer per checkpoint dir**: commit bookkeeping is recovered
    from the WAL once at construction and tracked in memory afterwards, so
    exactly one live ``StreamingQuery`` may own a checkpoint directory (the
    same contract Spark's ``MicroBatchExecution`` enforces via a run lock).
    Starting a second query on the same dir, or committing externally while
    one runs, yields stale bookkeeping — recover by constructing a fresh
    query, which re-scans the log.
    """

    _PROGRESS_KEEP = 100  # Spark keeps the last 100 progress records

    def __init__(
        self,
        model: Transformer,
        source: StreamSource,
        sink: StreamSink,
        checkpoint_dir: str,
        max_batch_offsets: Optional[int] = None,
        pipeline_depth: int = 2,
        wal_mode: str = "files",
    ):
        self.predictor = BatchPredictor(model)
        self.source = source
        self.sink = sink
        self.checkpoint_dir = checkpoint_dir
        self.max_batch_offsets = max_batch_offsets
        # up to pipeline_depth batches in flight: batch i+1's source read +
        # feature prep + device dispatch overlap batch i's device compute
        # and result transfer (JAX dispatch is async; only materialization
        # blocks).  Commits stay ordered AND happen only after the batch's
        # results reached the sink — the exactly-once contract is
        # unchanged; a crash leaves in-flight intents in the WAL, which a
        # restarted query replays exactly as Spark does.  Depth 1 disables
        # overlap.
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._in_flight: List[tuple] = []
        self._stopped = False
        # last _PROGRESS_KEEP committed batches' timing/rows (the
        # ``StreamingQueryProgress``/``recentProgress`` analog); durationMs
        # is WAL-intent→commit, i.e. true per-batch latency including
        # pipeline queue wait
        self.recentProgress: List[dict] = []
        if wal_mode not in ("files", "append"):
            raise ValueError("wal_mode must be 'files' or 'append'")
        self.wal_mode = wal_mode
        self._offsets_dir = os.path.join(checkpoint_dir, "offsets")
        self._commits_dir = os.path.join(checkpoint_dir, "commits")
        if wal_mode == "append":
            self._init_append_wal(checkpoint_dir)
        else:
            os.makedirs(self._offsets_dir, exist_ok=True)
            os.makedirs(self._commits_dir, exist_ok=True)
            self._pending_intents = None
            # recover bookkeeping from the log ONCE; afterwards the engine
            # tracks it in memory (the WAL files are still written per
            # batch — the directory scan per batch was pure overhead, not
            # durability)
            self._last_committed = self._scan_last_committed()
            self._end_offset = self._read_committed_end(self._last_committed)
        self._next_start = self._end_offset

    def _init_append_wal(self, checkpoint_dir: str) -> None:
        """``wal_mode='append'``: one JSONL log per side (intents /
        commits) with a single flushed append write per batch — the
        high-throughput WAL.  Same recovery contract as the per-file
        format (uncommitted logged intents replay on restart); the two
        formats are per-checkpoint-dir exclusive."""
        if os.path.isdir(self._offsets_dir) or os.path.isdir(
            self._commits_dir
        ):
            raise ValueError(
                f"checkpoint dir {checkpoint_dir!r} was written in "
                "'files' WAL mode; pick a fresh dir for 'append' mode"
            )
        os.makedirs(checkpoint_dir, exist_ok=True)
        offsets_path = os.path.join(checkpoint_dir, "offsets.log")
        commits_path = os.path.join(checkpoint_dir, "commits.log")

        def read_log(path):
            if not os.path.exists(path):
                return {}
            out = {}
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rec = json.loads(line)
                        out[int(rec["batch_id"])] = rec
            return out

        self._pending_intents = read_log(offsets_path)
        commits = read_log(commits_path)
        self._last_committed = max(commits) if commits else -1
        self._end_offset = (
            commits[self._last_committed]["end"] if commits else 0
        )
        self._offsets_log = open(offsets_path, "a")
        self._commits_log = open(commits_path, "a")

    # -- checkpoint bookkeeping -------------------------------------------

    def _log_ids(self, d: str) -> List[int]:
        return sorted(
            int(os.path.splitext(os.path.basename(p))[0])
            for p in glob.glob(os.path.join(d, "*.json"))
        )

    def _scan_last_committed(self) -> int:
        ids = self._log_ids(self._commits_dir)
        return ids[-1] if ids else -1

    def _read_committed_end(self, last: int) -> int:
        if last < 0:
            return 0
        with open(os.path.join(self._commits_dir, f"{last}.json")) as f:
            return json.load(f)["end"]

    def last_committed(self) -> int:
        return self._last_committed

    def _committed_end(self) -> int:
        return self._end_offset

    def _pending_intent(self, batch_id: int):
        if self._pending_intents is not None:  # append mode: in-memory
            return self._pending_intents.get(batch_id)
        path = os.path.join(self._offsets_dir, f"{batch_id}.json")
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        return None

    def _wal_intent(self, batch_id: int, intent: dict) -> None:
        if self.wal_mode == "append":
            self._offsets_log.write(json.dumps(intent) + "\n")
            self._offsets_log.flush()
            self._pending_intents[batch_id] = intent
        else:
            with open(
                os.path.join(self._offsets_dir, f"{batch_id}.json"), "w"
            ) as f:
                json.dump(intent, f)

    def _wal_commit(self, batch_id: int, intent: dict) -> None:
        if self.wal_mode == "append":
            self._commits_log.write(json.dumps(intent) + "\n")
            self._commits_log.flush()
            self._pending_intents.pop(batch_id, None)
        else:
            with open(
                os.path.join(self._commits_dir, f"{batch_id}.json"), "w"
            ) as f:
                json.dump(intent, f)

    # -- engine ------------------------------------------------------------

    def _dispatch_next(self) -> bool:
        """WAL + read + dispatch the next micro-batch (non-blocking);
        returns False if no new data."""
        batch_id = self.last_committed() + 1 + len(self._in_flight)
        intent = self._pending_intent(batch_id)
        if intent is None:
            start = self._next_start
            latest = self.source.latest_offset()
            if latest <= start:
                return False
            end = latest
            if self.max_batch_offsets is not None:
                end = min(end, start + self.max_batch_offsets)
            intent = {"batch_id": batch_id, "start": start, "end": end}
            # intent WAL before any processing (OffsetSeqLog)
            self._wal_intent(batch_id, intent)

        t0 = time.perf_counter()
        frame = self.source.get_batch(intent["start"], intent["end"])
        finalize = self.predictor.predict_frame_async(frame)
        self._in_flight.append((batch_id, intent, finalize, t0,
                                frame.num_rows))
        self._next_start = intent["end"]
        return True

    def _retire_oldest(self) -> None:
        """Materialize the oldest in-flight batch, sink it, commit.

        The entry leaves ``_in_flight`` only AFTER its commit file is
        written: if the sink raises, the batch stays queued and the next
        ``process_available`` retries it from its WAL'd intent — popping
        first would silently skip the batch and shift every later
        ``batch_id`` (exactly-once violation)."""
        batch_id, intent, finalize, t0, n_rows = self._in_flight[0]
        self.sink.add_batch(batch_id, finalize())
        self._wal_commit(batch_id, intent)
        self._in_flight.pop(0)
        self._last_committed = batch_id
        self._end_offset = intent["end"]
        dur = time.perf_counter() - t0
        self.recentProgress.append({
            "batchId": batch_id,
            "numInputRows": int(n_rows),
            "durationMs": dur * 1e3,
            "processedRowsPerSecond": (n_rows / dur) if dur > 0 else 0.0,
        })
        if len(self.recentProgress) > self._PROGRESS_KEEP:
            del self.recentProgress[0]

    def _run_one_batch(self) -> bool:
        """Advance the pipeline by one committed batch; returns False when
        no batch was committed (and nothing could be dispatched)."""
        while len(self._in_flight) < self.pipeline_depth:
            if not self._dispatch_next():
                break
        if self._in_flight:
            self._retire_oldest()
            return True
        return False

    def process_available(self) -> int:
        """Deterministically drain all currently-available data; returns the
        number of batches run (test/step API)."""
        n = 0
        while not self._stopped and self._run_one_batch():
            n += 1
        return n

    def run(
        self,
        poll_interval: float = 1.0,
        max_batches: Optional[int] = None,
    ) -> int:
        """Continuous micro-batch loop (the ``writeStream.start()`` analog,
        in the foreground)."""
        done = 0
        while not self._stopped:
            ran = self._run_one_batch()
            if ran:
                done += 1
                if max_batches is not None and done >= max_batches:
                    break
            else:
                time.sleep(poll_interval)
        return done

    # -- background lifecycle (Spark StreamingQuery surface) ---------------

    def start(self, poll_interval: float = 1.0) -> "StreamingQuery":
        """Run the micro-batch loop on a daemon thread and return
        immediately (Spark's ``writeStream.start()``); pair with
        :meth:`awaitTermination`/:meth:`stop`.  The engine stays a
        single writer — all batch work happens on the one loop thread;
        ``stop()`` flips the flag, JOINS the loop thread, and only then
        closes the append-WAL handles (never under the loop's feet)."""
        import threading

        if getattr(self, "_thread", None) is not None and self._thread.is_alive():
            raise RuntimeError("query already started")
        if self._stopped:
            raise RuntimeError("query was stopped; construct a new one")

        def _loop():
            try:
                self.run(poll_interval=poll_interval)
            except BaseException as e:  # surfaced by awaitTermination
                self._exception = e

        self._exception: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=_loop, name="sntc-streaming-query", daemon=True
        )
        self._thread.start()
        return self

    @property
    def isActive(self) -> bool:
        t = getattr(self, "_thread", None)
        return t is not None and t.is_alive()

    @property
    def lastProgress(self) -> Optional[dict]:
        return self.recentProgress[-1] if self.recentProgress else None

    def awaitTermination(self, timeout: Optional[float] = None) -> bool:
        """Block until the query stops (or ``timeout`` seconds pass);
        returns True if it terminated.  Re-raises a crash from the loop
        thread, as Spark's ``awaitTermination`` does."""
        t = getattr(self, "_thread", None)
        if t is not None:
            t.join(timeout)
            if not t.is_alive() and self._exception is not None:
                raise self._exception
            return not t.is_alive()
        return self._stopped

    def stop(self) -> None:
        was_active = self.isActive
        self._stopped = True
        try:
            if was_active:
                # the loop thread still uses the WAL handles; wait for it
                # to exit its current batch before closing them
                self._thread.join()
                if self._exception is not None:
                    raise self._exception
        finally:
            if self.wal_mode == "append":
                self._offsets_log.close()
                self._commits_log.close()
