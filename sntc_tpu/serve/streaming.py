"""Micro-batch streaming inference — the Structured-Streaming analog [B:11].

Behavioral spec: SURVEY.md §3.5/§5.4 mechanism 3 (upstream
``MicroBatchExecution`` + ``OffsetSeqLog``/``CommitLog`` [U]): the engine
loop resolves the source's latest offset, write-ahead-logs the intended
batch range (``offsets/<id>.json``), runs the batch through the model,
hands it to the sink, then commits (``commits/<id>.json``).  On restart
with the same checkpoint dir, an uncommitted intent is REPLAYED with its
logged range, giving exactly-once batches w.r.t. the offset log — Spark's
recovery contract.

Sources implement ``latest_offset()`` and ``get_batch(start, end)`` over a
monotonic integer offset (file count / row count — Spark's file-source
model).  ``process_available()`` steps the engine deterministically (the
``StreamTest`` harness analog, SURVEY.md §4).
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import List, Optional

import numpy as np

from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.data.ingest import load_csv
from sntc_tpu.data.pipeline import engine_meters, source_meters, timed
from sntc_tpu.obs import install_event_metrics
from sntc_tpu.obs.metrics import inc, observe, set_gauge
from sntc_tpu.obs.trace import span
from sntc_tpu.resilience import (
    RetryPolicy,
    emit_event,
    fault_point,
    with_retries,
)
from sntc_tpu.resilience.device import annotate_batch, classify_device_error
from sntc_tpu.resilience import storage as storage_plane
from sntc_tpu.serve.transform import BatchPredictor
from sntc_tpu.utils.profiling import TransferLedger, ledger_scope

# the event→metrics bridge rides every process that can serve: at
# MODULE import (not engine construction) so event-observer counts are
# deterministic for tests and ad-hoc emitters are covered too
install_event_metrics()


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------


class StreamSource:
    def latest_offset(self) -> int:
        raise NotImplementedError

    def get_batch(self, start: int, end: int) -> Frame:
        raise NotImplementedError


class DirStreamSource(StreamSource):
    """Shared machinery for directory-watching sources: offset = count of
    files in sorted order (the ``readStream`` file-source model: new files
    are new data).  Subclasses implement ``_load_file(path) -> Frame``.

    **One listing per poll tick**: ``latest_offset()`` globs+sorts once
    and caches the listing; the tick's ``get_batch`` reuses it whenever
    it covers the requested range (files are append-only in the offset
    model, so a listing that covers ``end`` is authoritative for it).

    **Parallel per-file reads**: multi-file batches fan the
    ``_load_file`` calls across a small thread pool (pyarrow's CSV/IPC
    readers release the GIL); concatenation order is by sorted filename,
    exactly as the serial path produced.

    **Prefetch** (``prefetch_batches=N``): :meth:`prefetch` stages a
    bounded background read of a future ``[start, end)`` range so the
    pipelined engine's next ``get_batch`` returns an already-parsed
    Frame.  Purely advisory — a range with no staged read falls through
    to the synchronous path, and a staged read that failed re-raises in
    ``get_batch`` where the engine's retry/fault machinery already
    wraps it.  ``N <= 0`` disables staging entirely (no threads).
    """

    def __init__(
        self,
        path: str,
        pattern: str,
        prefetch_batches: int = 0,
        read_workers: int = 4,
        parse_salvage: bool = False,
        tenant: Optional[str] = None,
    ):
        self.path = path
        self.pattern = pattern
        self.prefetch_batches = int(prefetch_batches)
        self.read_workers = max(1, int(read_workers))
        # the ingest source graph's source-side meters (read/parse/
        # stage; docs/PERFORMANCE.md "Autotuned ingest") — the
        # feedback signal the IngestAutotuner reads; ``tenant`` labels
        # their emitted series (the engine back-fills it for sources
        # built without one)
        self.tenant = tenant
        self.meters = source_meters(tenant)
        # parse_salvage=True arms per-line salvage in the file loaders
        # that support it (CSV): unparsable lines are excised at parse
        # time and collected as reject records — the engine drains them
        # via take_rejects() into its row-level dead-letter
        self.parse_salvage = bool(parse_salvage)
        self._listing: Optional[List[str]] = None
        self._read_pool = None
        self._prefetch_pool = None
        import threading

        # _pool() is reached from the engine thread (sync get_batch
        # miss) AND from prefetch threads (staged _read_range) — the
        # lazy create must not race two executors into existence
        self._pool_lock = threading.Lock()
        self._retired_pools: List = []  # resized-out executors (close())
        self._rejects_lock = threading.Lock()
        self._parse_rejects: List[dict] = []
        self._staged: dict = {}  # (start, end) -> Future[Frame]
        self.prefetch_hits = 0
        self.prefetch_misses = 0
        self.prefetch_hwm = 0  # staged-queue high-water mark

    def _files(self) -> List[str]:
        self._listing = sorted(
            glob.glob(os.path.join(self.path, self.pattern))
        )
        return self._listing

    def latest_offset(self) -> int:
        return len(self._files())

    def _load_file(self, path: str) -> Frame:
        raise NotImplementedError

    def _note_rejects(self, records: List[dict]) -> None:
        """Collect parse-time reject records (thread-safe: loaders run
        on read/prefetch pool threads)."""
        with self._rejects_lock:
            self._parse_rejects.extend(records)

    def take_rejects(self, files: Optional[List[str]] = None) -> List[dict]:
        """Drain the parse-time reject records collected since the last
        drain (the engine journals them into the row-level dead-letter
        with the batch that consumed the read).  ``files`` restricts the
        drain to records from those files — a prefetch thread may have
        parsed a FUTURE batch's file already, and its rejects must wait
        for the batch that actually covers that file."""
        with self._rejects_lock:
            if files is None:
                out = self._parse_rejects
                self._parse_rejects = []
                return out
            allowed = set(files)
            kept: List[dict] = []
            out: List[dict] = []
            for r in self._parse_rejects:
                if r.get("file") in allowed or r.get("file") is None:
                    out.append(r)
                else:
                    kept.append(r)
            self._parse_rejects = kept
            return out

    def files_for_range(self, start: int, end: int) -> List[str]:
        """The files a ``[start, end)`` batch covers, for dead-letter
        attribution (re-lists when the cached listing is stale)."""
        listing = self._listing
        if listing is None or len(listing) < end:
            listing = sorted(
                glob.glob(os.path.join(self.path, self.pattern))
            )
        return listing[start:end]

    def _pool(self):
        with self._pool_lock:
            if self._read_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._read_pool = ThreadPoolExecutor(
                    max_workers=self.read_workers,
                    thread_name_prefix="sntc-src-read",
                )
            return self._read_pool

    # -- live pool/queue resizing (the autotuner's action surface) -----------

    def set_read_workers(self, n: int) -> None:
        """Resize the per-file read pool live.  The old executor is
        RETIRED, not shut down: a prefetch thread may sit between
        ``_pool()`` returning it and ``.map()`` submitting to it, and
        an immediate shutdown would turn that knob resize into a
        spurious batch-read failure.  Retired pools drain their work
        and are closed at :meth:`close`; their count is bounded by the
        autotuner's no-oscillation change bound, so idle threads never
        accumulate past it."""
        n = max(1, int(n))
        with self._pool_lock:
            if n == self.read_workers:
                return
            self.read_workers = n
            old, self._read_pool = self._read_pool, None
            if old is not None:
                self._retired_pools.append(old)

    def set_prefetch_batches(self, n: int) -> None:
        """Resize the staging queue bound (and therefore the staging
        pool width) live.  Already-staged ranges stay staged — the
        bound applies to NEW prefetch calls; the old pool is retired
        exactly like :meth:`set_read_workers`'s."""
        n = max(0, int(n))
        with self._pool_lock:
            if n == self.prefetch_batches:
                return
            self.prefetch_batches = n
            old, self._prefetch_pool = self._prefetch_pool, None
            if old is not None:
                self._retired_pools.append(old)

    def _timed_load(self, path: str) -> Frame:
        return timed(self.meters["parse"], self._load_file, path)

    def _read_files(self, files: List[str]) -> Frame:
        if len(files) == 1:  # common micro-batch case: skip the concat copy
            return self._timed_load(files[0])
        return Frame.concat_all(
            list(self._pool().map(self._timed_load, files))
        )

    def _read_range(
        self, start: int, end: int, listing: Optional[List[str]]
    ) -> Frame:
        # a listing that does not cover ``end`` is re-scanned LOCALLY —
        # the prefetch thread must never mutate the cached listing under
        # the engine thread's feet
        if listing is None or len(listing) < end:
            listing = sorted(
                glob.glob(os.path.join(self.path, self.pattern))
            )
        files = listing[start:end]
        if not files:
            raise ValueError(f"empty batch range [{start}, {end})")
        return self._read_files(files)

    def prefetch(
        self, start: int, end: int, cursor: Optional[int] = None
    ) -> bool:
        """Stage a background read of ``[start, end)`` (bounded by
        ``prefetch_batches`` outstanding ranges, which is also the
        staging pool width — ranges parse CONCURRENTLY; pyarrow's reader
        releases the GIL); returns True when a read was scheduled.
        Staged ranges wholly behind ``cursor`` (the engine's planning
        cursor; default ``start``) are stale — a load shed skipped them
        — and are evicted first."""
        if self.prefetch_batches <= 0 or end <= start:
            return False
        horizon = start if cursor is None else cursor
        for key in [k for k in self._staged if k[1] <= horizon]:
            self._staged.pop(key).cancel()
        if (start, end) in self._staged:
            return False
        if len(self._staged) >= self.prefetch_batches:
            return False
        if self._prefetch_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._prefetch_pool = ThreadPoolExecutor(
                max_workers=max(1, min(self.prefetch_batches, 4)),
                thread_name_prefix="sntc-src-prefetch",
            )
        listing = (
            list(self._listing)
            if self._listing is not None and len(self._listing) >= end
            else None
        )
        self._staged[(start, end)] = self._prefetch_pool.submit(
            self._staged_read, start, end, listing
        )
        self.prefetch_hwm = max(self.prefetch_hwm, len(self._staged))
        self._queue_gauge()
        return True

    def _staged_read(self, start: int, end: int, listing) -> Frame:
        # the 'stage' operator: one background prefetch of a future
        # range, timed into its own meter (the parse meter still sees
        # the per-file decodes it fans out)
        return timed(
            self.meters["stage"], self._read_range, start, end, listing
        )

    def _queue_gauge(self) -> None:
        labels = {} if self.tenant is None else {"tenant": self.tenant}
        set_gauge(
            "sntc_ingest_queue_depth", len(self._staged),
            stage="stage", **labels,
        )

    def prefetch_stats(self) -> dict:
        return {
            "hits": self.prefetch_hits,
            "misses": self.prefetch_misses,
            "hwm": self.prefetch_hwm,
            "staged": len(self._staged),
        }

    def close(self) -> None:
        """Shut down the reader pools (idempotent; a closed source can
        still serve synchronous reads)."""
        self._staged.clear()
        with self._pool_lock:
            pools = [self._read_pool, self._prefetch_pool]
            pools.extend(self._retired_pools)
            self._retired_pools = []
            self._read_pool = self._prefetch_pool = None
        for pool in pools:
            if pool is not None:
                pool.shutdown(wait=True)

    def get_batch(self, start: int, end: int) -> Frame:
        t0 = time.perf_counter()
        try:
            fut = self._staged.pop((start, end), None)
            if fut is not None:
                self.prefetch_hits += 1
                inc("sntc_source_prefetch_hits_total")
                self._queue_gauge()
                # a failed staged read re-raises HERE, inside the
                # engine's stream.read retry/fault scope; the entry was
                # consumed, so a retry falls through to a fresh
                # synchronous read
                return fut.result()
            if self.prefetch_batches > 0:
                self.prefetch_misses += 1
                inc("sntc_source_prefetch_misses_total")
            listing = self._listing
            if listing is not None and len(listing) < end:
                listing = None  # stale: _read_range re-scans exactly once
            return self._read_range(start, end, listing)
        finally:
            # the 'read' operator: what the ENGINE waited for this
            # range — near-zero on a staged hit, the full inline parse
            # on a miss (the read-vs-parse gap is the autotuner's
            # staging signal)
            self.meters["read"].record(time.perf_counter() - t0)


class FileStreamSource(DirStreamSource):
    """Directory of flow CSVs.  With ``parse_salvage=True`` ragged
    lines are excised per-line (file + line number journaled) instead
    of failing the whole batch — see :func:`sntc_tpu.data.ingest
    .load_csv`.

    ``columnar=True`` parses through the zero-copy columnar plane
    (:func:`sntc_tpu.data.pipeline.read_flows_columnar` with
    ``handle_invalid=None``): every feature column is cast to float32
    ONCE inside Arrow at parse time and handed over as a zero-copy
    numpy view — exactly the dtype the fused predict programs' upload
    policy wants, so no host copy remains between parse and the single
    device upload.  Non-finite VALUES survive (as float32 NaN/Inf) for
    the admission layer to police; row policy stays admission's job."""

    def __init__(
        self,
        path: str,
        pattern: str = "*.csv",
        columnar: bool = False,
        **kwargs,
    ):
        super().__init__(path, pattern, **kwargs)
        self.columnar = bool(columnar)

    def _load_file(self, path: str) -> Frame:
        if self.columnar:
            from sntc_tpu.data.pipeline import read_flows_columnar

            recs: List[dict] = []
            frame = read_flows_columnar(
                path, handle_invalid=None,
                salvage=self.parse_salvage,
                rejects=recs if self.parse_salvage else None,
            )
            if recs:
                self._note_rejects(recs)
            return frame
        if not self.parse_salvage:
            return load_csv(path)
        recs = []
        frame = load_csv(path, salvage=True, rejects=recs)
        if recs:
            self._note_rejects(recs)
        return frame


class MemorySource(StreamSource):
    """In-memory list of Frames — the ``MemoryStream`` test analog."""

    def __init__(self, frames: Optional[List[Frame]] = None):
        self._frames: List[Frame] = list(frames or [])

    def add(self, frame: Frame) -> None:
        self._frames.append(frame)

    def latest_offset(self) -> int:
        return len(self._frames)

    def get_batch(self, start: int, end: int) -> Frame:
        if end - start == 1:  # skip the concat copy for 1-frame batches
            return self._frames[start]
        return Frame.concat_all(self._frames[start:end])


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------


class StreamSink:
    def add_batch(self, batch_id: int, frame: Frame) -> None:
        raise NotImplementedError


class MemorySink(StreamSink):
    def __init__(self):
        self.batches: List[tuple] = []

    def add_batch(self, batch_id: int, frame: Frame) -> None:
        self.batches.append((batch_id, frame))

    @property
    def frames(self) -> List[Frame]:
        return [f for _, f in self.batches]


class CsvDirSink(StreamSink):
    """One CSV per batch (append output mode).

    ``durable=True`` (the default) fsyncs the temp file before the
    rename publishes it: rename-without-fsync can expose an EMPTY or
    truncated ``batch_*.csv`` after a power loss even though the rename
    itself was atomic (the classic publish-before-data-reaches-disk
    bug; the process-kill chaos matrix can never catch it because the
    page cache survives a kill).  The fsync is real I/O latency on the
    retire stage — which the pipelined engine's delivery thread hides
    behind the next batch's read, while a serial engine stalls on it.
    ``durable=False`` restores the page-cache-speed publish for
    throwaway sinks (tests, dead-letter dumps)."""

    def __init__(
        self,
        path: str,
        columns: Optional[List[str]] = None,
        durable: bool = True,
    ):
        self.path = path
        self.columns = columns
        self.durable = bool(durable)
        os.makedirs(path, exist_ok=True)

    def add_batch(self, batch_id: int, frame: Frame) -> None:
        import pyarrow.csv as pacsv

        cols = self.columns or [
            c for c in frame.columns if frame[c].ndim == 1
        ]
        # atomic tmp-then-rename: a crash (or injected fault) mid-write
        # leaves no torn batch_*.csv for downstream readers to ingest.
        # The sink output is the PRODUCT, not a lifecycle-managed
        # artifact — it grows with the data served, by design.
        final = os.path.join(self.path, f"batch_{batch_id:06d}.csv")
        tmp = final + ".tmp"
        try:
            pacsv.write_csv(frame.select(cols).to_arrow(), tmp)
            if self.durable:
                fd = os.open(tmp, os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
            os.replace(tmp, final)  # storage: unbounded(sink output)
            if self.durable:
                # the rename is only durable once the DIRECTORY entry is
                # on disk — without this, power loss after commit can
                # lose the published file entirely (data fsynced, dirent
                # not)
                dfd = os.open(self.path, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
        except OSError as e:
            # partial-write attribution (r17, the PR-5 parser-error
            # discipline applied to sinks): name the file and how many
            # bytes landed before the failure, so an ENOSPC/EIO names
            # WHERE the disk died instead of a bare errno
            written = 0
            try:
                written = os.path.getsize(tmp)
            except OSError:
                pass
            raise OSError(
                e.errno,
                f"sink write for batch {batch_id} failed at {tmp} "
                f"({written} bytes written, {frame.num_rows} rows): "
                f"{e.strerror or e}",
                tmp,
            ) from e


class ConsoleSink(StreamSink):
    def add_batch(self, batch_id: int, frame: Frame) -> None:
        print(f"[batch {batch_id}] {frame}")


# ---------------------------------------------------------------------------
# the micro-batch engine
# ---------------------------------------------------------------------------


class StreamingQuery:
    """Micro-batch inference engine (SURVEY.md §3.5, §5.4 mechanism 3).

    **Single writer per checkpoint dir**: commit bookkeeping is recovered
    from the WAL once at construction and tracked in memory afterwards, so
    exactly one live ``StreamingQuery`` may own a checkpoint directory (the
    same contract Spark's ``MicroBatchExecution`` enforces via a run lock).
    Starting a second query on the same dir, or committing externally while
    one runs, yields stale bookkeeping — recover by constructing a fresh
    query, which re-scans the log.

    **Resilience (opt-in, defaults preserve single-shot semantics):**
    ``retry_policy`` arms per-site retry with deterministic backoff for
    source reads (``stream.read``) and sink delivery (``sink.write``).
    ``max_batch_failures=N`` arms poison-batch quarantine: after a batch
    has failed N retirement rounds (each round is a full retry cycle
    under the policy), it is journaled to the dead-letter sink
    (``<checkpoint_dir>/dead_letter/``) and COMMITTED so the query keeps
    going instead of dying — Spark's "skip bad records" degradation,
    with the evidence preserved.  Both sites call
    ``sntc_tpu.resilience.fault_point`` so tier-1 tests (or
    ``SNTC_FAULTS``) can inject failures deterministically.
    ``breakers={"sink.write": CircuitBreaker(...), "predict.dispatch":
    ...}`` arms per-site circuit breakers: an OPEN breaker defers the
    stage (batch stays queued, loop stays alive) instead of hammering a
    dead dependency; see ``sntc_tpu.resilience.circuit``.  The
    :class:`~sntc_tpu.resilience.supervisor.QuerySupervisor` layers
    admission control (load shedding), a batch watchdog, and
    preemption-safe drain on top of this engine.

    **Row admission (opt-in, r10):** ``schema_contract=SchemaContract``
    validates every read batch against per-column dtype / NaN / Inf /
    range / domain policies (``row_policy`` overrides the contract's
    mode).  ``strict`` fails the batch on any violation (the poison-
    batch machinery above owns it); ``salvage``/``permissive`` excise
    only the poison rows — via the SAME row-validity mask that bucket
    padding threads, applied inside the already-bucketed frame, so
    excision never changes a dispatched shape and the jitted/fused
    predict programs never recompile.  Excised rows land in a row-level
    dead-letter (``row_dead_letter_dir``, default
    ``<checkpoint_dir>/dead_letter_rows/``) with batch id, source file,
    row/line, raw text, and a machine-readable reason code, and a
    ``rows_rejected`` event rides the structured stream (HealthMonitor
    marks the source DEGRADED).  See docs/RESILIENCE.md "Data-plane
    admission".

    **Pipelined mode (opt-in):** ``overlap_sink=True`` moves the retire
    stage (finalize + ``sink.add_batch``, with its retry cycle) onto a
    dedicated delivery thread so batch N's sink write overlaps batch
    N+1's source read and predict dispatch; ``shape_buckets=N`` pads
    micro-batches up to power-of-two row buckets (floor N) so the jitted
    predict compiles once per bucket (see
    :class:`~sntc_tpu.serve.transform.BatchPredictor`); a source with a
    ``prefetch`` method (``DirStreamSource(prefetch_batches=...)``) is
    additionally hinted each round to stage the NEXT batch's read in the
    background.  The protocol order is UNCHANGED: WAL intent → read →
    dispatch → sink → commit, commits stay on the engine thread in batch
    order, at most ONE delivery is in the air, and the head batch leaves
    ``_in_flight`` only after its commit lands — so breaker, quarantine,
    drain, and crash-replay semantics are exactly the serial engine's
    (the chaos matrix runs unchanged in pipelined mode).  See
    ``docs/PERFORMANCE.md``.

    **Multi-tenant namespacing (opt-in, r12):** ``tenant="<id>"`` —
    set by the :class:`~sntc_tpu.serve.tenancy.ServeDaemon` — prefixes
    every site this engine touches with ``tenant/<id>/``: retry,
    quarantine, shed, and reject events (which also carry a ``tenant``
    field), health components derived from them, and ``fault_point``
    lookups (a fault armed at ``tenant/<id>/stream.wal`` fires only
    for this engine; a bare-site fault still hits every tenant).  The
    checkpoint/WAL/dead-letter directories are whatever the caller
    passes — the daemon namespaces those too.  Single-tenant engines
    (the default) are byte-for-byte unchanged.
    """

    _PROGRESS_KEEP = 100  # Spark keeps the last 100 progress records

    def __init__(
        self,
        model: Transformer,
        source: StreamSource,
        sink: StreamSink,
        checkpoint_dir: str,
        max_batch_offsets: Optional[int] = None,
        pipeline_depth: int = 2,
        shape_buckets: int = 0,
        overlap_sink: bool = False,
        wal_mode: str = "files",
        retry_policy: Optional[RetryPolicy] = None,
        max_batch_failures: Optional[int] = None,
        dead_letter_dir: Optional[str] = None,
        breakers: Optional[dict] = None,
        schema_contract=None,
        row_policy: Optional[str] = None,
        row_dead_letter_dir: Optional[str] = None,
        lifecycle=None,
        tenant: Optional[str] = None,
        autotuner=None,
        wal_compact_every: int = 256,
        wal_keep_commits: int = 64,
        dead_letter_keep: int = 200,
        commit_listener=None,
    ):
        # a pre-built BatchPredictor passes through unchanged (its own
        # bucket config wins — bench warmup shares one predictor across
        # the warmup and measured queries so compile_events is one ledger)
        self.predictor = (
            model
            if isinstance(model, BatchPredictor)
            else BatchPredictor(model, bucket_rows=shape_buckets)
        )
        self.shape_buckets = int(self.predictor.bucket_rows)
        self.source = source
        self.sink = sink
        self.checkpoint_dir = checkpoint_dir
        # post-commit hook (r23): called AFTER the commit record is
        # durable, with (batch_id, intent, n_rows).  The warm-standby
        # ReplicationPlane rides here to ship artifacts and seal its
        # commit barrier; listener failures are contained — a broken
        # listener never fails a committed batch.
        self.commit_listener = commit_listener
        self.max_batch_offsets = max_batch_offsets
        # up to pipeline_depth batches in flight: batch i+1's source read +
        # feature prep + device dispatch overlap batch i's device compute
        # and result transfer (JAX dispatch is async; only materialization
        # blocks).  Commits stay ordered AND happen only after the batch's
        # results reached the sink — the exactly-once contract is
        # unchanged; a crash leaves in-flight intents in the WAL, which a
        # restarted query replays exactly as Spark does.  Depth 1 disables
        # overlap.
        self.pipeline_depth = max(1, int(pipeline_depth))
        # overlap mode: the retire stage runs on ONE dedicated delivery
        # thread; the engine thread keeps planning/reading/dispatching
        # while it runs and settles the outcome (commit / defer /
        # quarantine) back on the engine thread — single WAL writer
        self.overlap_sink = bool(overlap_sink)
        self._delivery = None  # (batch_id, Future) while one is in the air
        self._delivery_pool = None
        self._delivery_busy_s = 0.0  # wall time the retire stage ran
        self._delivered_batches = 0
        self._tick_latest: Optional[int] = None
        self.retry_policy = retry_policy
        if max_batch_failures is not None and max_batch_failures < 1:
            raise ValueError("max_batch_failures must be >= 1 (or None)")
        self.max_batch_failures = max_batch_failures
        self.dead_letter_dir = dead_letter_dir or os.path.join(
            checkpoint_dir, "dead_letter"
        )
        # data-plane admission (r10): a SchemaContract validates every
        # read batch.  strict = any violation fails the batch (the
        # poison-batch machinery above takes over); salvage/permissive =
        # poison rows are excised via the row-validity mask INSIDE the
        # already-bucketed frame (no shape change, no recompile) and
        # journaled row-by-row to the dead-letter below
        self.schema_contract = schema_contract
        if row_policy is not None and schema_contract is None:
            raise ValueError(
                "row_policy requires a schema_contract to enforce"
            )
        self.row_policy = row_policy or (
            schema_contract.mode if schema_contract is not None else None
        )
        self.row_dead_letter_dir = row_dead_letter_dir or os.path.join(
            checkpoint_dir, "dead_letter_rows"
        )
        self._rows_rejected_total = 0
        self._rows_coerced_total = 0
        self._batches_salvaged = 0
        self._rows_journaled: set = set()  # batch ids already journaled
        self._admission_counted: set = set()  # batch ids stat-counted
        # model lifecycle (r11): a duck-typed hook object — usually a
        # sntc_tpu.lifecycle.LifecycleManager — observing every clean
        # committed batch (on_batch), checked once per engine round
        # (on_tick), and supplying deferred hot-swaps
        # (take_pending_swap / on_swap_applied).  Swaps land only
        # BETWEEN micro-batches; see swap_model().
        self.lifecycle = lifecycle
        self.models_swapped = 0
        # multi-tenant namespacing (r12): a ``tenant`` id prefixes every
        # site this engine emits against — retry/quarantine/shed events,
        # breaker-adjacent health components, and fault_point lookups
        # all become ``tenant/<id>/<site>`` — so one tenant's failures,
        # breakers, and health can never alias a neighbor's.  The map is
        # precomputed once; the single-tenant path (tenant=None) keeps
        # the bare site strings and adds no per-event work.
        self.tenant = tenant
        _known_sites = (
            "stream.wal", "stream.read", "stream.commit",
            "sink.write", "predict.dispatch", "source.parse",
        )
        self._sites = {
            s: (s if tenant is None else f"tenant/{tenant}/{s}")
            for s in _known_sites
        }
        # observability (r13): the engine's own transfer ledger —
        # scoped around every predict dispatch so fused-segment
        # uploads/downloads attribute to THIS engine (and, when
        # tenanted, to its sntc_transfer_*{tenant=...} series) instead
        # of conflating in the process-global view; the engine-emitted
        # metrics (batches/rows/duration) carry the same tenant label.
        self.transfer = TransferLedger(tenant=tenant)
        self._mlabels = {} if tenant is None else {"tenant": tenant}
        # the ingest source graph (r15): engine-side stage meters
        # (admit/bucket) complete the source's read/parse/stage set;
        # a tenant-less source built outside the daemon inherits this
        # engine's tenant label so its series attribute correctly
        self.ingest_meters = engine_meters(tenant)
        src_meters = getattr(source, "meters", None)
        if tenant is not None and src_meters is not None:
            if getattr(source, "tenant", None) is None:
                source.tenant = tenant
                for m in src_meters.values():
                    m.tenant = tenant
        # optional feedback autotuner (sntc_tpu.data.autotune): ticked
        # once per engine round — poll-tick cadence — to resize
        # read_workers / prefetch width / pipeline depth from the
        # observed stage-latency and backpressure profile.  Failures
        # degrade (autotune_error event), never kill the loop.
        self.autotuner = autotuner
        # per-site circuit breakers (sink.write / predict.dispatch): an
        # OPEN breaker defers the stage — the batch stays queued and the
        # loop stays alive — instead of hammering a dead dependency
        self.breakers: dict = dict(breakers or {})
        self._batch_failures: dict = {}
        # batches whose quarantine evidence is already journaled but
        # whose COMMIT deferred (transient WAL failure): the next round
        # must not re-quarantine them — duplicate dead-letter records
        # and a second quarantine event (= a second tenant strike)
        self._quarantined_ids: set = set()
        self._in_flight: List[tuple] = []
        self._sample_next: Optional[int] = None  # stride for next intent
        self._stopped = False
        # last _PROGRESS_KEEP committed batches' timing/rows (the
        # ``StreamingQueryProgress``/``recentProgress`` analog); durationMs
        # is WAL-intent→commit, i.e. true per-batch latency including
        # pipeline queue wait
        self.recentProgress: List[dict] = []
        if wal_mode not in ("files", "append"):
            raise ValueError("wal_mode must be 'files' or 'append'")
        self.wal_mode = wal_mode
        # durable-storage lifecycle (r17): every artifact under this
        # checkpoint dir is BOUNDED — the append WAL compacts into a
        # sealed checkpoint every ``wal_compact_every`` commits, the
        # files-mode WAL prunes committed intent/commit pairs beyond
        # ``wal_keep_commits``, journals rotate at a size cap, and the
        # dead-letter dirs keep the newest ``dead_letter_keep`` batch
        # dumps.  0 disables the respective bound (the pre-r17
        # grow-forever behavior, for equivalence tests).
        self.wal_compact_every = max(0, int(wal_compact_every))
        self.wal_keep_commits = max(0, int(wal_keep_commits))
        self.dead_letter_keep = max(0, int(dead_letter_keep))
        self._commits_since_compact = 0
        self.wal_compactions = 0
        self.wal_prunes = 0
        self._shed_writer = None
        self._dead_letter_writer = None
        # the light construction-time doctor: repair torn journal tails
        # and sweep tmp orphans a previous crash left (never fatal; the
        # WAL's own torn-tail repair lives in its reader below)
        self.storage_scan = storage_plane.quick_scan(
            checkpoint_dir, tenant=tenant
        )
        self._offsets_dir = os.path.join(checkpoint_dir, "offsets")
        self._commits_dir = os.path.join(checkpoint_dir, "commits")
        if wal_mode == "append":
            self._init_append_wal(checkpoint_dir)
        else:
            os.makedirs(self._offsets_dir, exist_ok=True)
            os.makedirs(self._commits_dir, exist_ok=True)
            self._pending_intents = None
            # recover bookkeeping from the log ONCE; afterwards the engine
            # tracks it in memory (the WAL files are still written per
            # batch — the directory scan per batch was pure overhead, not
            # durability)
            self._last_committed = self._scan_last_committed()
            self._end_offset = self._read_committed_end(self._last_committed)
            ids = self._log_ids(self._commits_dir)
            self._prune_cursor = ids[0] if ids else 0
        self._next_start = self._end_offset
        # stateful sources (sntc_tpu/flow): rewind operator state to
        # the snapshot matching the recovered committed offset BEFORE
        # any WAL replay dispatches — replay then reconverges bitwise
        restore = getattr(source, "on_restore", None)
        if restore is not None:
            restore(self._end_offset)

    def _init_append_wal(self, checkpoint_dir: str) -> None:
        """``wal_mode='append'``: one JSONL log per side (intents /
        commits) with a single flushed append write per batch — the
        high-throughput WAL.  Same recovery contract as the per-file
        format (uncommitted logged intents replay on restart); the two
        formats are per-checkpoint-dir exclusive.

        **Torn-tail repair (r17):** a crash mid-append leaves a partial
        final line; recovery tolerates exactly that shape — the torn
        tail is truncated out with a journaled repair record
        (``storage_repair.jsonl``) instead of crashing the restart with
        a ``JSONDecodeError``.  A torn intent is a batch that was never
        fully planned (it replans); a torn commit is a batch whose
        commit never landed (it replays; the sink dedupes) — both are
        the crash contract the WAL already promises.

        **Compaction (r17):** recovery is ``wal_checkpoint.json`` (a
        sealed summary of everything the logs said at the last
        compaction: last committed batch, end offset, pending intents)
        plus the log TAILS written since.  Records the checkpoint
        already covers replay idempotently, so a crash between the
        checkpoint publish and the log truncation recovers identically.
        """
        if os.path.isdir(self._offsets_dir) or os.path.isdir(
            self._commits_dir
        ):
            raise ValueError(
                f"checkpoint dir {checkpoint_dir!r} was written in "
                "'files' WAL mode; pick a fresh dir for 'append' mode"
            )
        os.makedirs(checkpoint_dir, exist_ok=True)
        offsets_path = os.path.join(checkpoint_dir, "offsets.log")
        commits_path = os.path.join(checkpoint_dir, "commits.log")
        self._wal_ckpt_path = os.path.join(
            checkpoint_dir, "wal_checkpoint.json"
        )
        base_last, base_end = -1, 0
        pending: dict = {}
        if os.path.exists(self._wal_ckpt_path):
            core = storage_plane.load_sealed_json(self._wal_ckpt_path)
            base_last = int(core["last_committed"])
            base_end = int(core["end"])
            pending = {
                int(k): v for k, v in core.get("pending", {}).items()
            }

        def read_log(path):
            records, _repair = storage_plane.read_jsonl_tolerant(
                path, repair=True, artifact="wal_append",
                tenant=self.tenant, repair_dir=checkpoint_dir,
            )
            return {int(rec["batch_id"]): rec for rec in records}

        for bid, rec in read_log(offsets_path).items():
            pending[bid] = rec
        commits = read_log(commits_path)
        if commits and max(commits) > base_last:
            base_last = max(commits)
            base_end = commits[base_last]["end"]
        self._last_committed = base_last
        self._end_offset = base_end
        # intents at/below the committed horizon are history, not
        # replay work — keeping them would only grow memory with uptime
        self._pending_intents = {
            bid: rec for bid, rec in pending.items()
            if bid > self._last_committed
        }
        self._offsets_log = open(offsets_path, "a")  # storage: wal_append
        self._commits_log = open(commits_path, "a")  # storage: wal_append

    # -- checkpoint bookkeeping -------------------------------------------

    def _log_ids(self, d: str) -> List[int]:
        return sorted(
            int(os.path.splitext(os.path.basename(p))[0])
            for p in glob.glob(os.path.join(d, "*.json"))
        )

    def _scan_last_committed(self) -> int:
        ids = self._log_ids(self._commits_dir)
        while ids:
            last = ids[-1]
            path = os.path.join(self._commits_dir, f"{last}.json")
            try:
                with open(path) as f:
                    json.load(f)
                return last
            except ValueError:
                # a torn commit record is a commit that never fully
                # landed: quarantine the evidence and fall back to the
                # previous one — the batch replays, the sink dedupes
                # (the crash contract, applied at recovery time)
                storage_plane.quarantine_blob(
                    path, artifact="wal_files",
                    detail="torn commit record at recovery",
                    root=self.checkpoint_dir, tenant=self.tenant,
                )
                ids.pop()
        return -1

    def _read_committed_end(self, last: int) -> int:
        if last < 0:
            return 0
        with open(os.path.join(self._commits_dir, f"{last}.json")) as f:
            return json.load(f)["end"]

    def last_committed(self) -> int:
        return self._last_committed

    def _emit(self, **fields) -> None:
        """Engine event emission: tenant-tagged when this engine serves
        a tenant (the daemon's fair-share / shed evidence reads the tag
        back out of the stream), a plain pass-through otherwise."""
        if self.tenant is not None:
            fields["tenant"] = self.tenant
        emit_event(**fields)

    def _pending_intent(self, batch_id: int):
        if self._pending_intents is not None:  # append mode: in-memory
            return self._pending_intents.get(batch_id)
        path = os.path.join(self._offsets_dir, f"{batch_id}.json")
        if os.path.exists(path):
            try:
                with open(path) as f:
                    return json.load(f)
            except ValueError:
                # a torn intent record is a batch that was never fully
                # planned: it replans from scratch, exactly as if the
                # crash had landed one instruction earlier
                return None
        return None

    def _append_log(self, attr: str, name: str):
        """The live append-WAL handle, reopened lazily ("a", never
        truncating) if a failed compaction left it closed — a sick disk
        degrades compaction, it must not strand the WAL behind a dead
        handle forever."""
        f = getattr(self, attr)
        if f is None or f.closed:
            f = open(  # storage: wal_append
                os.path.join(self.checkpoint_dir, name), "a"
            )
            setattr(self, attr, f)
        return f

    def _wal_intent(self, batch_id: int, intent: dict) -> None:
        if self.wal_mode == "append":
            # the PHYSICAL write boundary: storage.wal injects
            # enospc/io_error/torn_write here; policy FAIL — the error
            # propagates into the dispatch loop's per-batch failure
            # machinery (retry next round, quarantine at the threshold)
            storage_plane.append_line(
                self._append_log("_offsets_log", "offsets.log"),
                json.dumps(intent) + "\n",
                site="storage.wal", tenant=self.tenant,
            )
            self._pending_intents[batch_id] = intent
        else:
            storage_plane.atomic_write_json(
                os.path.join(self._offsets_dir, f"{batch_id}.json"),
                intent, site="storage.wal", tenant=self.tenant,
                fsync=False,
            )

    def _wal_commit(self, batch_id: int, intent: dict) -> None:
        if self.wal_mode == "append":
            storage_plane.append_line(
                self._append_log("_commits_log", "commits.log"),
                json.dumps(intent) + "\n",
                site="storage.wal", tenant=self.tenant,
            )
            self._pending_intents.pop(batch_id, None)
            # the caller's bookkeeping (_last_committed/_end_offset)
            # updates after this write returns — pass the just-committed
            # state explicitly so the sealed checkpoint can never trail
            # the log it replaces
            self._maybe_compact_wal(batch_id, intent["end"])
        else:
            storage_plane.atomic_write_json(
                os.path.join(self._commits_dir, f"{batch_id}.json"),
                intent, site="storage.wal", tenant=self.tenant,
                fsync=False,
            )
            self._prune_files_wal(batch_id)

    # -- WAL lifecycle (r17): compaction / pruning ---------------------------

    def _maybe_compact_wal(self, last_committed: int, end: int) -> None:
        """Append-mode compaction: every ``wal_compact_every`` commits,
        seal the recovered-state summary (last committed batch, end
        offset, pending intents) into an atomic ``wal_checkpoint.json``
        and truncate both logs — replay becomes checkpoint + tail, and
        the log footprint is bounded by the compaction interval instead
        of the query's lifetime.  A compaction that cannot write
        DEGRADES (counted, the logs simply keep growing until the disk
        recovers) — bounding storage must never lose the WAL."""
        if self.wal_compact_every <= 0:
            return
        self._commits_since_compact += 1
        if self._commits_since_compact < self.wal_compact_every:
            return
        core = {
            "version": 1,
            "last_committed": last_committed,
            "end": end,
            "pending": {
                str(bid): rec
                for bid, rec in self._pending_intents.items()
            },
        }
        try:
            storage_plane.atomic_write_json(
                self._wal_ckpt_path, storage_plane.seal_record(core),
                site="storage.wal", tenant=self.tenant,
            )
            # the checkpoint is durable: the logs' history is now
            # redundant.  A crash between here and the truncations
            # replays the tails over the checkpoint idempotently — and
            # so does a PARTIAL truncation (one log reopened, the other
            # failed): records the checkpoint covers replay as no-ops.
            # A failed reopen leaves the handle closed; the next write
            # reopens it lazily in append mode (_append_log), so a sick
            # disk degrades compaction without stranding the WAL.
            for attr, name in (
                ("_offsets_log", "offsets.log"),
                ("_commits_log", "commits.log"),
            ):
                getattr(self, attr).close()
                setattr(self, attr, open(  # storage: wal_append
                    os.path.join(self.checkpoint_dir, name), "w"
                ))
        except OSError as e:
            storage_plane.note_write_error(
                "wal_append", self._wal_ckpt_path, e, tenant=self.tenant
            )
            return
        storage_plane.note_write_ok("wal_append", tenant=self.tenant)
        self._commits_since_compact = 0
        self.wal_compactions += 1
        labels = {} if self.tenant is None else {"tenant": self.tenant}
        inc("sntc_wal_compactions_total", **labels)

    def _prune_files_wal(self, batch_id: int) -> None:
        """Files-mode retention: committed intent/commit PAIRS below
        the ``wal_keep_commits`` horizon are deleted (one pair per
        commit in steady state — O(1)).  Uncommitted intents are above
        the horizon by construction (every batch id at or below
        ``last committed - keep`` has a commit record), so replay
        evidence is never pruned."""
        if self.wal_keep_commits <= 0:
            return
        horizon = batch_id - self.wal_keep_commits
        while self._prune_cursor <= horizon:
            bid = self._prune_cursor
            for d in (self._offsets_dir, self._commits_dir):
                try:
                    os.unlink(os.path.join(d, f"{bid}.json"))
                    self.wal_prunes += 1
                except OSError:
                    pass
            self._prune_cursor += 1

    # -- engine ------------------------------------------------------------

    def _plan_end(self, start: int, latest: int) -> int:
        """THE batch-range rule: how far past ``start`` one micro-batch
        may reach given ``latest`` available offsets.  Single source of
        truth shared by the intent planner and both prefetch-hint sites
        — a hint computed by any other rule would never hit the range
        the planner actually dispatches."""
        end = latest
        if self.max_batch_offsets is not None:
            end = min(end, start + self.max_batch_offsets)
        return end

    def _dispatch_next(self) -> bool:
        """WAL + read + dispatch the next micro-batch (non-blocking);
        returns False if no new data."""
        batch_id = self.last_committed() + 1 + len(self._in_flight)
        intent = self._pending_intent(batch_id)
        if intent is None:
            start = self._next_start
            latest = self.source.latest_offset()
            self._tick_latest = latest  # reused by the prefetch hint
            if latest <= start:
                return False
            end = self._plan_end(start, latest)
            intent = {"batch_id": batch_id, "start": start, "end": end}
            if self._sample_next is not None:
                # sample-shed recovery batch: cover the WHOLE backlog in
                # one intent at reduced row resolution; the stride lives
                # in the intent so a crash replays the same sample
                intent["end"] = latest
                intent["sample_stride"] = self._sample_next
                self._sample_next = None
            try:
                # kill point pre-WAL: a crash here leaves NO intent —
                # the restarted query plans the batch fresh (chaos
                # matrix row 1)
                fault_point("stream.wal", tenant=self.tenant)
                # intent WAL before any processing (OffsetSeqLog)
                with span("stream.wal", batch=batch_id):
                    self._wal_intent(batch_id, intent)
            except Exception as e:
                # WAL failure policy (r17): FAIL into the existing
                # per-batch machinery — an unwritable intent defers the
                # batch (retry next round; transient ENOSPC recovers)
                # and quarantines at the threshold.  Unarmed engines
                # keep the r5 single-shot raise.
                fails = self._bump_failures(batch_id, "stream.wal")
                if self.max_batch_failures is None:
                    raise
                if fails < self.max_batch_failures or self._in_flight:
                    return False
                self._quarantine(batch_id, intent, None, e,
                                 site="stream.wal")
                self._commit_batch(batch_id, intent, n_rows=0,
                                   t0=time.perf_counter(),
                                   quarantined=True)
                self._next_start = max(self._next_start, intent["end"])
                return True

        # stage the FOLLOWING range before this batch's read blocks: the
        # prefetch thread parses batch N+1 while this round waits on
        # batch N's (staged) read — back-to-back reads, no round-trip
        # stall (no-op for sources without prefetch)
        pf = getattr(self.source, "prefetch", None)
        if pf is not None and self._tick_latest is not None:
            nxt = intent["end"]
            if self._tick_latest > nxt:
                pf(nxt, self._plan_end(nxt, self._tick_latest),
                   self._next_start)

        t0 = time.perf_counter()

        def _read() -> tuple:
            fault_point("stream.read", tenant=self.tenant)
            with span("stream.read", batch=batch_id):
                frame = self.source.get_batch(
                    intent["start"], intent["end"]
                )
            stride = intent.get("sample_stride", 1)
            if stride > 1:
                frame = frame.take(np.arange(0, frame.num_rows, stride))
            # one listing snapshot serves the selective drain AND the
            # journal's file attribution (journaling from a second,
            # later listing could name a different snapshot)
            files_for = getattr(self.source, "files_for_range", None)
            batch_files = (
                files_for(intent["start"], intent["end"])
                if files_for is not None
                else None
            )
            # drain parse-time rejects (per-line CSV salvage) BEFORE
            # admission so a read retry cannot leave them stranded —
            # restricted to THIS batch's files, because a prefetch
            # thread may already have parsed (and rejected lines from)
            # a future batch's file
            take = getattr(self.source, "take_rejects", None)
            rejects = list(take(batch_files)) if take is not None else []
            mask = None
            if self.schema_contract is not None:
                original = frame
                # strict mode raises SchemaViolation here — the batch
                # fails exactly like any other stream.read poison and
                # the retry/quarantine machinery owns it
                with span("stream.admit", batch=batch_id):
                    res = timed(
                        self.ingest_meters["admit"],
                        self.schema_contract.admit,
                        frame, mode=self.row_policy,
                    )
                frame = res.frame
                if not res.valid.all():
                    mask = res.valid
                if res.rejects:
                    # best-effort raw text: the row's 1-D values in
                    # column order (the parser layer records the true
                    # raw line for the lines it excised itself).  The
                    # column arrays are hoisted once — a poison-heavy
                    # batch must not pay a per-reject column walk
                    cols_1d = [
                        original[c] for c in original.columns
                        if original[c].ndim == 1
                    ]
                    for r in res.rejects:
                        rec = dict(r)
                        row = rec["row"]
                        rec["raw"] = ",".join(
                            str(a[row]) for a in cols_1d
                        )
                        rejects.append(rec)
                return frame, mask, rejects, res.coerced, batch_files
            return frame, mask, rejects, 0, batch_files

        frame = None
        stage = "stream.read"
        # fail-fast while the predict breaker is OPEN: deferring is the
        # certain outcome, so don't re-read (and re-retry) the whole
        # batch each poll tick just to discard it.  A state check, not
        # allow(): reserving a half-open probe slot here would leak it
        # if the read failed before dispatch.
        br_predict = self.breakers.get("predict.dispatch")
        if br_predict is not None and br_predict.state == "open":
            return False
        try:
            frame, row_mask, rejects, coerced, batch_files = (
                with_retries(_read, self.retry_policy,
                             site=self._sites["stream.read"])
                if self.retry_policy is not None
                else _read()
            )
            # eager models (the host micro-batch path) compute the whole
            # prediction HERE — a malformed batch is as much a poison
            # batch as a sink failure and must quarantine, not kill
            stage = "predict.dispatch"
            if br_predict is not None and not br_predict.allow():
                return False  # breaker open: defer, intent replays later
            # the journal write is idempotent per batch id (atomic
            # rewrite), so a WAL replay or sink-retry round cannot
            # double-count rejected rows
            if rejects:
                self._journal_rejected_rows(
                    batch_id, intent, rejects, batch_files or []
                )
            if batch_id not in self._admission_counted:
                # a deferred batch re-reads on its retry round — count
                # its admission outcome once, not once per round
                self._admission_counted.add(batch_id)
                if row_mask is not None:
                    self._batches_salvaged += 1
                self._rows_coerced_total += coerced
            try:
                # the engine's ledger is scoped around the dispatch:
                # fused-segment transfers attribute to this engine even
                # though their finalize may run on the delivery thread
                # (the segment captures the scope at dispatch)
                with ledger_scope(self.transfer), span(
                    "predict.dispatch", batch=batch_id
                ):
                    finalize = timed(
                        self.ingest_meters["bucket"],
                        self.predictor.predict_frame_async,
                        frame, row_valid=row_mask,
                    )
            except Exception as de:
                # a device-attributed failure is a PLATFORM fault: it
                # must not open the predict breaker (breaker_open is a
                # tenant-strike event, and the device belongs to the
                # platform, not the tenant) — but a half-open probe
                # slot allow() reserved must be RELEASED, not leaked,
                # or the breaker wedges half-open forever
                if br_predict is not None:
                    if (
                        self._device_domain() is not None
                        and classify_device_error(de) is not None
                    ):
                        br_predict.release()
                    else:
                        br_predict.record_failure()
                raise
            if br_predict is not None:
                br_predict.record_success()
        except Exception as e:
            dom = self._device_domain()
            if dom is not None:
                kind = classify_device_error(e)
                if kind is not None:
                    # dispatch-scope classification (r18): the batch is
                    # NOT poison — the platform is.  No failure bump, no
                    # quarantine, no tenant strike: the domain absorbs
                    # the fault (split / poison / HOST_DEGRADED) and the
                    # deferred batch replays next round through the
                    # response path.  Errors reaching here are the
                    # terminal shapes the predictor could not absorb
                    # in-place (e.g. an at-floor OOM before degradation).
                    if not getattr(e, "_sntc_device_counted", False):
                        dom.note_fault(
                            kind, site=self._sites["predict.dispatch"],
                            batch_id=batch_id,
                        )
                    return False
            fails = self._bump_failures(batch_id, stage)
            if self.max_batch_failures is None:
                raise  # quarantine unarmed: r5 single-shot semantics
            if fails < self.max_batch_failures or self._in_flight:
                # below the threshold (or older in-flight batches must
                # commit first — commit order is the restart-recovery
                # contract): stop dispatching this round and retry next
                # round WITHOUT killing the engine loop
                return False
            self._quarantine(batch_id, intent, frame, e, site=stage)
            self._commit_batch(batch_id, intent, n_rows=0, t0=t0,
                               quarantined=True)
            self._next_start = max(self._next_start, intent["end"])
            return True
        self._in_flight.append((batch_id, intent, finalize, t0,
                                frame.num_rows, frame, row_mask))
        # max(): a replayed WAL intent can end BELOW a cursor that an
        # 'oldest' shed already advanced — moving it back would undo the
        # journaled shed and double-count it on the next tick
        self._next_start = max(self._next_start, intent["end"])
        return True

    def _device_domain(self):
        """The predictor's compute-plane fault domain (None when
        unarmed) — shared across every engine serving this predictor,
        exactly as the tenants share the physical device."""
        return getattr(self.predictor, "device_domain", None)

    def _bump_failures(self, batch_id: int, stage: str) -> int:
        """Per-(batch, stage) failure rounds: a read flake and a sink
        flake on the same batch must not pool toward one threshold."""
        key = (batch_id, stage)
        self._batch_failures[key] = self._batch_failures.get(key, 0) + 1
        return self._batch_failures[key]

    def _clear_failures(self, batch_id: int) -> None:
        for key in [k for k in self._batch_failures if k[0] == batch_id]:
            del self._batch_failures[key]

    def _deliver_head(self, batch_id: int, finalize) -> None:
        """The retire stage's WORK: materialize the batch (finalize) and
        hand it to the sink, under the retry policy.  Runs on the engine
        thread serially, or on the delivery thread in overlap mode; the
        outcome is settled by :meth:`_settle_head` on the engine thread
        either way."""
        t0 = time.perf_counter()
        try:

            def _deliver() -> None:
                fault_point("sink.write", tenant=self.tenant)
                try:
                    self.sink.add_batch(batch_id, finalize())
                except Exception as e:
                    # finalize runs HERE — on the delivery thread in
                    # overlap mode — where a device-side error would
                    # otherwise surface with no batch context; thread
                    # the batch id through the chain (the fused
                    # segment already added segment + signature)
                    raise annotate_batch(e, batch_id)

            with span("sink.deliver", batch=batch_id):
                if self.retry_policy is not None:
                    with_retries(_deliver, self.retry_policy,
                                 site=self._sites["sink.write"])
                else:
                    _deliver()
        finally:
            self._delivery_busy_s += time.perf_counter() - t0

    def _settle_head(self, exc: Optional[BaseException]) -> bool:
        """Outcome bookkeeping for ONE retirement round of the head
        batch (``exc`` is the delivery failure, or None on success):
        breaker outcome, failure-round accounting, quarantine at the
        threshold, commit.  The entry leaves ``_in_flight`` only AFTER
        its commit file is written — a failed round leaves it queued, so
        batch ids never shift (exactly-once).  Returns True when the
        batch committed (normally or quarantined)."""
        (batch_id, intent, finalize, t0, n_rows, frame,
         row_mask) = self._in_flight[0]
        breaker = self.breakers.get("sink.write")
        quarantined = False
        if exc is not None:
            dom = self._device_domain()
            kind = (
                classify_device_error(exc) if dom is not None else None
            )
            if kind is not None:
                # a device failure surfacing at finalize/delivery is a
                # PLATFORM fault: it never scores the sink breaker —
                # release the reserved half-open probe slot (a leaked
                # slot would wedge the breaker half-open forever; a
                # recorded failure would open it on evidence the sink
                # never produced).  Note the fault (degrading the
                # domain on repeats) and RE-DISPATCH the head through
                # the response path: the memoized finalize cached the
                # device failure, only a fresh dispatch can take the
                # split/fallback route.
                if breaker is not None:
                    breaker.release()
                if not getattr(exc, "_sntc_device_counted", False):
                    dom.note_fault(
                        kind, site=self._sites["predict.dispatch"],
                        batch_id=batch_id,
                    )
                fails = self._bump_failures(batch_id, "device.dispatch")
                limit = (
                    (self.max_batch_failures or 1)
                    + dom.policy.degrade_after
                )
                if fails <= limit and frame is not None:
                    self._redispatch_head()
                    return False
                # the safety valve: even the host fallback keeps dying
                # device-shaped.  Quarantine attributed to the DEVICE
                # path — never to the sink the failure rode in on
                if self.max_batch_failures is None:
                    raise exc  # unarmed: r5 single-shot semantics
                if batch_id not in self._quarantined_ids:
                    self._quarantine(batch_id, intent, frame, exc,
                                     site="predict.dispatch")
                    self._quarantined_ids.add(batch_id)
                quarantined = True
            else:
                # one breaker outcome per retirement ROUND (a failure
                # that survived the whole retry cycle is real trouble)
                if breaker is not None:
                    breaker.record_failure()
                fails = self._bump_failures(batch_id, "sink.write")
                if self.max_batch_failures is None:
                    # quarantine unarmed: r5 single-shot semantics
                    raise exc
                if fails < self.max_batch_failures:
                    return False  # stays queued; retried next round
                if batch_id not in self._quarantined_ids:
                    self._quarantine(batch_id, intent, frame, exc,
                                     site="sink.write")
                    self._quarantined_ids.add(batch_id)
                quarantined = True
        else:
            if breaker is not None:
                breaker.record_success()
        try:
            self._commit_batch(batch_id, intent, n_rows=n_rows, t0=t0,
                               quarantined=quarantined)
        except Exception as ce:
            # WAL-commit failure policy (r17): the sink already has the
            # batch, only the commit record is missing — defer (the
            # batch stays queued; next round re-delivers and the sink
            # dedupes, then retries the commit) below the threshold.
            # Persistent commit failure raises: exactly-once cannot
            # survive a WAL that never writes again.
            fails = self._bump_failures(batch_id, "stream.commit")
            if (
                self.max_batch_failures is None
                or fails >= self.max_batch_failures
            ):
                raise ce
            return False
        self._in_flight.pop(0)
        self._quarantined_ids.discard(batch_id)
        self._delivered_batches += 1
        if not quarantined and self.lifecycle is not None:
            # drift scoring / shadow promotion observe the committed
            # batch (finalize is memoized — a cached read, not a
            # re-materialization).  A lifecycle hook failure degrades,
            # never kills, the serving loop.  Under row salvage the
            # admitted frame is filtered to the SURVIVING rows so its
            # labels align row-for-row with finalize()'s output (which
            # excises the same mask).
            try:
                lc_frame = (
                    frame if row_mask is None else frame.filter(row_mask)
                )
                self.lifecycle.on_batch(batch_id, lc_frame, finalize)
            except Exception as e:
                self._emit(
                    event="lifecycle_error", component="model",
                    batch_id=batch_id, error=repr(e),
                )
        return True

    def _redispatch_head(self) -> None:
        """Replace the head batch's (failed, failure-memoized) finalize
        with a FRESH predictor dispatch of its stored frame — the
        device response ladder (split / poisoned-signature fallback /
        HOST_DEGRADED host path) can only engage on a new dispatch.
        Failures here degrade (the old finalize stays; the next
        settle round classifies again), never kill."""
        (batch_id, intent, _old, t0, n_rows, frame,
         row_mask) = self._in_flight[0]
        try:
            with ledger_scope(self.transfer):
                fin = self.predictor.predict_frame_async(
                    frame, row_valid=row_mask
                )
            self._in_flight[0] = (
                batch_id, intent, fin, t0, n_rows, frame, row_mask
            )
        except Exception as e:
            self._emit(
                event="device_error", batch_id=batch_id,
                error=repr(e), during="redispatch",
            )

    def _retire_oldest(self) -> bool:
        """Serial retire: materialize the oldest in-flight batch, sink
        it, commit — one retirement round on the engine thread.

        With ``max_batch_failures=N`` armed, failed rounds below the
        threshold DEFER (the batch stays queued, the engine loop stays
        alive — under ``run()``/``start()`` each poll tick is one retry
        round) and the N-th failed round quarantines the batch
        (dead-letter journal + commit) so the query continues.  Returns
        True when a batch was committed."""
        batch_id, _intent, finalize = self._in_flight[0][:3]
        breaker = self.breakers.get("sink.write")
        if breaker is not None and not breaker.allow():
            return False  # breaker open: batch stays queued, loop alive
        exc: Optional[BaseException] = None
        try:
            self._deliver_head(batch_id, finalize)
        except Exception as e:
            exc = e
        return self._settle_head(exc)

    # -- overlapped retire (pipelined mode) ---------------------------------

    def _submit_delivery(self) -> bool:
        """Arm the delivery thread with the head batch's retire work.
        The sink breaker's ``allow()`` is consumed here (one reservation
        per round, outcome recorded at settle); an OPEN breaker defers
        exactly as in the serial path."""
        batch_id, _intent, finalize = self._in_flight[0][:3]
        breaker = self.breakers.get("sink.write")
        if breaker is not None and not breaker.allow():
            return False
        if self._delivery_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._delivery_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="sntc-sink-delivery"
            )
        self._delivery = (
            batch_id,
            self._delivery_pool.submit(self._deliver_head, batch_id,
                                       finalize),
        )
        return True

    def _finish_delivery(self, wait: bool) -> bool:
        """Settle the in-air delivery (joining it when ``wait``);
        returns True when its batch committed.  Settlement — commit,
        deferral bookkeeping, or quarantine — runs on the engine thread,
        so the WAL keeps its single writer."""
        if self._delivery is None:
            return False
        batch_id, fut = self._delivery
        if not wait and not fut.done():
            return False
        exc = fut.exception()  # joins the delivery when wait=True
        self._delivery = None
        if not self._in_flight or self._in_flight[0][0] != batch_id:
            raise RuntimeError(
                f"delivery settled for batch {batch_id} but the queue "
                "head moved — pipeline invariant violated"
            )
        return self._settle_head(exc)

    def _pump_delivery(self) -> None:
        """One overlap-mode pump: settle a completed delivery, then
        (re)arm the delivery thread with the current head so the next
        retire runs while the engine thread plans/reads/dispatches."""
        self._finish_delivery(wait=False)
        if self._delivery is None and self._in_flight:
            self._submit_delivery()

    def _maybe_prefetch(self) -> None:
        """Hint the source to stage the UPCOMING batches' reads in the
        background (no-op for sources without ``prefetch``).  Up to the
        source's staging capacity, ranges are hinted in dispatch order:
        replayed WAL intents use their logged ranges, then the planned
        ranges from this tick's offset read — exactly the ranges
        ``_dispatch_next`` will request, so the staged Frames are hits.
        Purely advisory; a hint the planner diverges from just misses."""
        pf = getattr(self.source, "prefetch", None)
        if pf is None:
            return
        cursor = self._next_start
        capacity = max(1, int(getattr(self.source, "prefetch_batches", 1)))
        bid = self.last_committed() + 1 + len(self._in_flight)
        start = self._next_start
        for _ in range(capacity):
            intent = self._pending_intent(bid)
            if intent is not None:
                pf(intent["start"], intent["end"], cursor)
                start = max(start, intent["end"])
                bid += 1
                continue
            latest = self._tick_latest
            if latest is None or latest <= start:
                break
            end = self._plan_end(start, latest)
            pf(start, end, cursor)
            start = end
            bid += 1

    # -- model lifecycle (hot-swap) ------------------------------------------

    def swap_model(self, model: Transformer) -> Transformer:
        """Atomic in-engine hot-swap: replace the served model BETWEEN
        micro-batches, keeping the predictor's bucket config and
        compile ledger (`BatchPredictor.swap_model`).

        A swap must NEVER land while a sink delivery is in the air
        (``overlap_sink`` mode): the head batch is settled first —
        commit, deferral, or quarantine on this thread — and only then
        does the predictor flip.  Batches already dispatched finalize
        against the model they were dispatched with; the swap takes
        effect from the next dispatch.  Returns the replaced model.
        Call from the engine thread only (the loop applies lifecycle
        swaps via its own safe point; tests drive it directly between
        ``process_available`` steps)."""
        if self._delivery is not None:
            # settle the in-air delivery first: its finalize is bound
            # to the old model's dispatch and its outcome bookkeeping
            # must complete under the old generation
            self._finish_delivery(wait=True)
        if self._delivery is not None:  # pragma: no cover - invariant
            raise RuntimeError(
                "model swap attempted with a delivery still in air"
            )
        old = self.predictor.swap_model(model)
        self.models_swapped += 1
        return old

    def _lifecycle_tick(self) -> None:
        """Once per engine round: probation checks, then apply any
        pending hot-swap at this between-batches safe point.  The same
        degrade-never-kill contract as ``on_batch``: a failure anywhere
        in the tick (probation rollback I/O, the swap itself) emits
        ``lifecycle_error`` instead of killing the serving loop."""
        lc = self.lifecycle
        if lc is None:
            return
        pending = None
        try:
            on_tick = getattr(lc, "on_tick", None)
            if on_tick is not None:
                on_tick(self)
            take = getattr(lc, "take_pending_swap", None)
            pending = take() if take is not None else None
            if pending is not None:
                old = self.swap_model(pending)
                # the flip landed: past this point a failure must NOT
                # re-arm (retrying would swap the same model twice)
                pending = None
                applied = getattr(lc, "on_swap_applied", None)
                if applied is not None:
                    applied(old)
        except Exception as e:
            if pending is not None:
                # the safe point failed BEFORE the predictor flip —
                # put the swap back so the next tick retries instead
                # of silently dropping it (a dropped rollback would
                # wedge the promoter in "rolling_back" while the disk
                # checkpoint already names the restored model)
                rearm = getattr(lc, "rearm_pending_swap", None)
                if rearm is not None:
                    rearm(pending)
            self._emit(
                event="lifecycle_error", component="model",
                error=repr(e),
            )

    def pipeline_stats(self) -> dict:
        """Pipelining evidence (the bench journal's ``pipeline`` field):
        overlap/bucket config, delivery-thread busy time, predict-shape
        compile counters, and the source's prefetch stats when it has
        any."""
        stats = {
            "overlap_sink": self.overlap_sink,
            "pipeline_depth": self.pipeline_depth,
            "shape_buckets": self.shape_buckets,
            "delivery_busy_s": round(self._delivery_busy_s, 6),
            "delivered_batches": self._delivered_batches,
            "compile_events": self.predictor.compile_events,
            "bucket_hits": self.predictor.bucket_hits,
            "padded_rows_total": self.predictor.padded_rows_total,
        }
        stats["transfers"] = self.transfer.snapshot()
        src_stats = getattr(self.source, "prefetch_stats", None)
        if src_stats is not None:
            stats["prefetch"] = src_stats()
        # the source graph's per-stage meters (read/parse/stage from
        # the source, admit/bucket from this engine) + any autotuner
        # evidence — the config-10 bench journal reads these
        ingest = {
            name: m.snapshot()
            for name, m in getattr(self.source, "meters", {}).items()
        }
        ingest.update(
            (name, m.snapshot())
            for name, m in self.ingest_meters.items()
        )
        stats["ingest"] = ingest
        if self.autotuner is not None:
            stats["autotune"] = self.autotuner.stats()
        fusion = self.predictor.fusion_stats()
        if fusion is not None:
            stats["fusion"] = fusion
        dom = self._device_domain()
        if dom is not None:
            stats["device"] = dom.stats()
        admission = self.admission_stats()
        if admission is not None:
            stats["admission"] = admission
        stats["storage"] = self.storage_stats()
        if self.lifecycle is not None:
            lc_stats = getattr(self.lifecycle, "stats", None)
            stats["lifecycle"] = dict(
                lc_stats() if lc_stats is not None else {},
                models_swapped=self.models_swapped,
            )
        return stats

    def storage_stats(self) -> dict:
        """Durable-storage lifecycle evidence for THIS engine's
        checkpoint dir: WAL bound config + compaction/prune counters,
        journal-writer health, and the construction-time scan verdict.
        The supervisor/daemon ``storage`` status block layers the
        disk-usage measurements (``StoragePlane``) on top."""
        out = {
            "wal_mode": self.wal_mode,
            "wal_compact_every": self.wal_compact_every,
            "wal_keep_commits": self.wal_keep_commits,
            "dead_letter_keep": self.dead_letter_keep,
            "wal_compactions": self.wal_compactions,
            "wal_prunes": self.wal_prunes,
        }
        for name, writer in (
            ("shed_journal", self._shed_writer),
            ("dead_letter_journal", self._dead_letter_writer),
        ):
            if writer is not None:
                out[name] = writer.stats()
        if self.storage_scan is not None and (
            self.storage_scan["repaired"]
            or self.storage_scan["errors"]
            or self.storage_scan["cleaned"]
        ):
            out["startup_scan"] = {
                k: self.storage_scan[k]
                for k in ("repaired", "errors", "cleaned")
            }
        return out

    def _commit_batch(self, batch_id: int, intent: dict, *, n_rows: int,
                      t0: float, quarantined: bool) -> None:
        """The ONE commit protocol (WAL commit + bookkeeping + progress
        record), shared by normal retirement and both quarantine paths
        so restart-recovery state can never diverge between them."""
        # stateful sources publish their operator-state snapshot BEFORE
        # the commit record is written: the two retained snapshots then
        # always bracket the committed offset, so a crash anywhere in
        # between restores the exact-offset snapshot and the replayed
        # batch reconsumes from it (sntc_tpu/flow/source.py)
        committed_hook = getattr(self.source, "on_batch_committed", None)
        if committed_hook is not None:
            committed_hook(batch_id, intent)
        # kill point post-sink/pre-commit: results reached the sink but
        # the commit never lands — the restarted query must REPLAY the
        # batch from its WAL'd intent and the sink must dedupe (chaos
        # matrix row 3)
        fault_point("stream.commit", tenant=self.tenant)
        with span("stream.commit", batch=batch_id):
            self._wal_commit(batch_id, intent)
        self._clear_failures(batch_id)
        # a committed batch never re-reads in this process — drop its
        # admission-idempotence bookkeeping so the sets stay bounded by
        # the in-flight window, not the query's lifetime
        self._rows_journaled.discard(batch_id)
        self._admission_counted.discard(batch_id)
        self._last_committed = batch_id
        self._end_offset = intent["end"]
        if self.commit_listener is not None:
            try:
                self.commit_listener(batch_id, intent, n_rows)
            except Exception as e:
                emit_event(
                    event="commit_listener_error", tenant=self.tenant,
                    batch_id=batch_id, error=repr(e),
                )
        dur = time.perf_counter() - t0
        # per-batch engine metrics (tenant-labeled when serving one):
        # the commit is the ONE place every batch passes exactly once
        inc("sntc_batches_committed_total", **self._mlabels)
        if n_rows:
            inc("sntc_rows_committed_total", n_rows, **self._mlabels)
        observe("sntc_batch_duration_seconds", dur, **self._mlabels)
        progress = {
            "batchId": batch_id,
            "numInputRows": int(n_rows),
            "durationMs": dur * 1e3,
            "processedRowsPerSecond": (n_rows / dur) if dur > 0 else 0.0,
        }
        if quarantined:
            progress["quarantined"] = True
        self.recentProgress.append(progress)
        if len(self.recentProgress) > self._PROGRESS_KEEP:
            del self.recentProgress[0]

    def _journal_rejected_rows(
        self, batch_id: int, intent: dict, rejects: List[dict],
        batch_files: List[str],
    ) -> None:
        """The ROW-level dead-letter: one JSONL file per batch under
        ``row_dead_letter_dir``, each record carrying batch_id, source
        file (exact for parse-time rejects; the batch's file list
        otherwise), row index/line number, raw text, and a
        machine-readable reason code.  Written atomically and keyed by
        batch id, so a WAL replay rewrites — never duplicates — the
        evidence; a ``rows_rejected`` event rides the structured stream
        so :class:`~sntc_tpu.resilience.health.HealthMonitor` marks the
        source DEGRADED on rising reject rates.  ``batch_files`` is the
        listing snapshot the read itself used (one glob serves drain
        and attribution)."""
        seen: set = set()
        records: List[dict] = []
        for r in rejects:
            key = (
                r.get("file"), r.get("line"), r.get("row"), r.get("raw"),
                r.get("reason"),
            )
            if key in seen:  # a retried read re-parses the same lines
                continue
            seen.add(key)
            rec = {
                "batch_id": batch_id,
                "file": r.get("file") or (
                    batch_files[0] if len(batch_files) == 1 else None
                ),
                "line": r.get("line"),
                "row": r.get("row"),
                "raw": r.get("raw"),
                "reason": r.get("reason"),
                "column": r.get("column"),
                "value": r.get("value"),
                "detail": r.get("detail"),
                "ts": time.time(),
            }
            if rec["file"] is None and batch_files:
                rec["batch_files"] = batch_files
            records.append(rec)
        if not records:
            return
        # a deferred batch re-reads (and re-admits) on its retry round:
        # rewrite the evidence, but never double-count it
        first_journal = batch_id not in self._rows_journaled
        self._rows_journaled.add(batch_id)
        os.makedirs(self.row_dead_letter_dir, exist_ok=True)
        final = os.path.join(
            self.row_dead_letter_dir, f"batch_{batch_id:06d}.jsonl"
        )
        if os.path.exists(final):
            # merge with what an earlier round (or a pre-crash run, on
            # WAL replay) journaled — a rewrite must never SHRINK the
            # evidence (e.g. a record a prefetch thread attributed here
            # before the selective drain existed)
            def _key(r):
                return (
                    r.get("file"), r.get("line"), r.get("row"),
                    r.get("raw"), r.get("reason"),
                )

            with open(final) as f:
                prior = [
                    json.loads(line) for line in f if line.strip()
                ]
            fresh = {_key(r) for r in records}
            records = [
                r for r in prior if _key(r) not in fresh
            ] + records
        try:
            # atomic + idempotent on WAL replay; the storage.dead_letter
            # site injects disk faults here, and the failure policy is
            # SHED: evidence that cannot be journaled is counted and
            # dropped — it must never fail the batch it describes
            storage_plane.atomic_write_bytes(
                final,
                "".join(json.dumps(rec) + "\n" for rec in records).encode(),
                site="storage.dead_letter", tenant=self.tenant,
                fsync=False,
            )
        except OSError as e:
            storage_plane.note_write_error(
                "dead_letter_rows", final, e, tenant=self.tenant,
            )
            return
        storage_plane.note_write_ok("dead_letter_rows", tenant=self.tenant)
        if self.dead_letter_keep > 0:
            storage_plane.prune_dir_keep_newest(
                self.row_dead_letter_dir, self.dead_letter_keep,
                artifact="dead_letter_rows", tenant=self.tenant,
            )
        if not first_journal:
            return
        self._rows_rejected_total += len(records)
        reasons: dict = {}
        for rec in records:
            reasons[rec["reason"]] = reasons.get(rec["reason"], 0) + 1
        self._emit(
            event="rows_rejected", site=self._sites["source.parse"],
            batch_id=batch_id, count=len(records), reasons=reasons,
        )

    def admission_stats(self) -> Optional[dict]:
        """Row-admission evidence (None when no contract is armed):
        active policy, rows rejected/coerced, batches that needed the
        salvage mask, and the row dead-letter location."""
        if self.schema_contract is None:
            return None
        return {
            "policy": self.row_policy,
            "rows_rejected": self._rows_rejected_total,
            "rows_coerced": self._rows_coerced_total,
            "batches_salvaged": self._batches_salvaged,
            "row_dead_letter_dir": self.row_dead_letter_dir,
        }

    def _quarantine(
        self, batch_id: int, intent: dict, frame: Optional[Frame],
        exc: BaseException, site: str = "sink.write",
    ) -> None:
        """Journal the poison batch to the dead-letter sink: one JSONL
        record (intent + error) always; the raw 1-D input columns as a
        CSV alongside when dumpable.  The batch is then committed by the
        caller — the query degrades instead of dying, and the evidence
        survives for replay/repair tooling."""
        os.makedirs(self.dead_letter_dir, exist_ok=True)
        record = {
            "batch_id": batch_id,
            "intent": intent,
            "error": repr(exc),
            "failures": sum(
                v for k, v in self._batch_failures.items()
                if k[0] == batch_id
            ),
            "num_rows": int(frame.num_rows) if frame is not None else None,
            "ts": time.time(),
            "rows_file": None,
        }
        if frame is not None:
            try:
                # reuse the atomic CSV sink for the raw-rows dump —
                # best-effort evidence, page-cache speed (durable=False),
                # like the dead_letter.jsonl record beside it
                CsvDirSink(
                    self.dead_letter_dir, durable=False
                ).add_batch(batch_id, frame)
                record["rows_file"] = f"batch_{batch_id:06d}.csv"
            except Exception as dump_err:
                record["dump_error"] = repr(dump_err)
        # the record journal rotates at a size cap and DEGRADES on disk
        # failure (buffered in memory, flushed on recovery) — losing a
        # quarantine record must never kill the quarantine itself
        if self._dead_letter_writer is None:
            self._dead_letter_writer = storage_plane.RotatingJsonlWriter(
                os.path.join(self.dead_letter_dir, "dead_letter.jsonl"),
                artifact="dead_letter", tenant=self.tenant,
            )
        self._dead_letter_writer.write(record)
        if self.dead_letter_keep > 0:
            storage_plane.prune_dir_keep_newest(
                self.dead_letter_dir, self.dead_letter_keep,
                artifact="dead_letter", tenant=self.tenant,
                protect=tuple(
                    f"dead_letter.jsonl{s}" for s in ("", ".1", ".2")
                ),
            )
        self._emit(
            event="quarantine", site=self._sites.get(site, site),
            batch_id=batch_id, error=repr(exc),
        )

    def _run_one_batch(self) -> bool:
        """Advance the pipeline by one committed batch; returns False when
        no batch was committed (and nothing could be dispatched).  A
        read-poison batch quarantined inside the dispatch loop counts as
        progress too (it commits without ever entering the pipeline).

        Overlap mode pumps the delivery thread BEFORE the dispatch loop
        (so the head batch's finalize+sink runs while this round reads
        and dispatches the next batches) and again after it (a delivery
        that finished during the dispatch window commits now)."""
        before = self._last_committed
        self._lifecycle_tick()
        dom = self._device_domain()
        if dom is not None:
            # the probe-gated recovery tick (cheap when DEVICE_OK);
            # degrade-never-kill like the lifecycle/autotune ticks
            try:
                dom.tick()
            except Exception as e:
                self._emit(event="device_error", error=repr(e),
                           during="tick")
        if self.autotuner is not None:
            # poll-tick cadence; same degrade-never-kill contract as
            # the lifecycle tick — a controller bug must not stop
            # serving (and knob changes land only between batches)
            try:
                self.autotuner.on_tick(self)
            except Exception as e:
                self._emit(event="autotune_error", error=repr(e))
        if self.overlap_sink:
            self._pump_delivery()
            if self._tick_latest is None:
                # first round: one listing up front so the initial
                # dispatches hit staged reads instead of parsing cold
                self._tick_latest = self.source.latest_offset()
            self._maybe_prefetch()
        while len(self._in_flight) < self.pipeline_depth:
            if not self._dispatch_next():
                break
            if self.overlap_sink:
                # re-arm between dispatches: a delivery that finished
                # while this round blocked on a read settles now and the
                # next dispatched batch goes straight onto the delivery
                # thread instead of idling until the round ends
                self._pump_delivery()
        self._maybe_prefetch()
        if self.overlap_sink:
            self._pump_delivery()
        elif self._in_flight:
            self._retire_oldest()
        return self._last_committed != before

    def process_available(self) -> int:
        """Deterministically drain all currently-available data; returns the
        number of batches COMMITTED (test/step API) — counted by commit
        delta, so a read-quarantined batch that commits inside the
        dispatch loop is included.  In overlap mode a round with nothing
        left to dispatch JOINS the in-air delivery instead of returning
        with it unsettled — the drained guarantee is identical to the
        serial engine's."""
        start = self._last_committed
        while not self._stopped:
            if self._run_one_batch():
                continue
            if self.overlap_sink and self._delivery is not None:
                # idle except for the in-air delivery: join and settle it
                # (commit, deferral bookkeeping, or quarantine), then
                # loop — a deferred round re-arms and eventually either
                # commits, quarantines, or trips the breaker open
                self._finish_delivery(wait=True)
                continue
            break
        return self._last_committed - start

    # -- supervision hooks (QuerySupervisor surface) ------------------------

    def backlog_offsets(self, latest: Optional[int] = None) -> int:
        """Source offsets available but not yet covered by any intent.
        ``latest`` lets a supervising loop reuse one per-tick source
        offset read instead of re-scanning the source."""
        if latest is None:
            latest = self.source.latest_offset()
        return max(0, latest - self._next_start)

    def in_flight_count(self) -> int:
        """Dispatched-but-uncommitted batches (the drain tail length)."""
        return len(self._in_flight)

    def committed_end(self) -> int:
        """End offset of the last committed batch (the resume point)."""
        return self._end_offset

    def planned_offset(self) -> int:
        """The planning cursor: offsets below it are committed, in
        flight, or shed; offsets at/above it are unplanned backlog."""
        return self._next_start

    def shed_backlog(
        self,
        max_pending_batches: int,
        policy: str = "oldest",
        latest: Optional[int] = None,
    ) -> Optional[dict]:
        """Admission control: when the pending backlog exceeds
        ``max_pending_batches`` micro-batches (batch =
        ``max_batch_offsets`` source offsets; one offset when unset),
        shed down to the cap and return the journaled record, else None.

        ``"oldest"`` drops the oldest surplus offsets outright (the
        freshest data keeps flowing); ``"sample"`` marks the next
        intent to cover the WHOLE backlog with a deterministic row
        stride (``sample_stride``), trading resolution for coverage.
        Either way the decision is appended to
        ``<checkpoint>/shed.jsonl`` and emitted as a ``load_shed``
        event.  Shedding is an in-memory flow decision, not a commit: a
        crash before the next commit restores the backlog and the
        supervisor simply sheds again on restart.
        """
        if policy not in ("oldest", "sample"):
            raise ValueError("shed policy must be 'oldest' or 'sample'")
        if self._sample_next is not None:
            # a sample decision is already pending consumption (dispatch
            # deferred by an open breaker, say): re-shedding every poll
            # tick would journal duplicate records and flood the event
            # ring with load_shed noise for ONE backlog decision
            return None
        unit = self.max_batch_offsets or 1
        if latest is None:  # caller may pass its own per-tick read
            latest = self.source.latest_offset()
        # offsets covered by uncommitted WAL intents WILL be replayed
        # regardless (the exactly-once contract) — they are not
        # sheddable, and journaling them as dropped would over-report
        base = self._next_start
        bid = self.last_committed() + 1 + len(self._in_flight)
        while True:
            replay = self._pending_intent(bid)
            if replay is None:
                break
            base = max(base, replay["end"])
            bid += 1
        pending = latest - base
        keep = max_pending_batches * unit
        if pending <= keep:
            return None
        record = {
            "ts": time.time(),
            "policy": policy,
            "backlog_offsets": pending,
            "max_pending_batches": max_pending_batches,
        }
        if self.tenant is not None:
            # shed.jsonl must say WHICH tenant paid for the decision —
            # the daemon's fair-share evidence reads it back
            record["tenant"] = self.tenant
        if policy == "oldest":
            shed_end = latest - keep
            record.update(
                start=base, end=shed_end,
                offsets_shed=shed_end - base,
            )
            self._next_start = max(self._next_start, shed_end)
        else:  # sample
            stride = -(-pending // keep)  # ceil: keeps ~keep offsets' rows
            record.update(
                start=base, end=latest, sample_stride=stride,
                offsets_shed=0,
            )
            self._sample_next = stride
        # rotating + DEGRADE policy (r17): a shed decision that cannot
        # journal still sheds — the record buffers and flushes when the
        # disk recovers, behind a counted storage_degraded episode
        if self._shed_writer is None:
            self._shed_writer = storage_plane.RotatingJsonlWriter(
                os.path.join(self.checkpoint_dir, "shed.jsonl"),
                artifact="shed_journal", tenant=self.tenant,
            )
        self._shed_writer.write(record)
        self._emit(
            event="load_shed", site=self._sites["stream.read"],
            policy=policy,
            start=record["start"], end=record["end"],
            offsets_shed=record["offsets_shed"],
            sample_stride=record.get("sample_stride"),
        )
        return record

    def drain(self) -> int:
        """Finish and commit every in-flight batch WITHOUT dispatching
        new ones (the preemption-drain tail).  Returns batches
        committed.  Retirement rounds that keep deferring (open
        breaker, quarantine threshold not yet reached) are bounded —
        anything left uncommitted stays in the WAL for the restarted
        query to replay, which is the same contract a crash has."""
        before = self._last_committed
        stalled_rounds = 0
        max_stalled = ((self.max_batch_failures or 1) + 1) * (
            len(self._in_flight) + 1
        )
        while self._in_flight and stalled_rounds < max_stalled:
            if self.overlap_sink:
                if self._delivery is None and not self._submit_delivery():
                    stalled_rounds += 1  # breaker open: defer
                    continue
                committed = self._finish_delivery(wait=True)
            else:
                committed = self._retire_oldest()
            if committed:
                stalled_rounds = 0
            else:
                stalled_rounds += 1
        return self._last_committed - before

    def run(
        self,
        poll_interval: float = 1.0,
        max_batches: Optional[int] = None,
    ) -> int:
        """Continuous micro-batch loop (the ``writeStream.start()`` analog,
        in the foreground).  Counts batches by commit delta — one round
        can commit several read-quarantined batches; a deferred round
        (quarantine armed, threshold not reached) sleeps and retries."""
        done = 0
        while not self._stopped:
            before = self._last_committed
            self._run_one_batch()
            if (
                self.overlap_sink
                and self._delivery is not None
                and self._last_committed == before
            ):
                # idle except for the in-air delivery: join it rather
                # than sleeping past its completion
                self._finish_delivery(wait=True)
            delta = self._last_committed - before
            if delta:
                done += delta
                if max_batches is not None and done >= max_batches:
                    break
            else:
                time.sleep(poll_interval)
        return done

    # -- background lifecycle (Spark StreamingQuery surface) ---------------

    def start(self, poll_interval: float = 1.0) -> "StreamingQuery":
        """Run the micro-batch loop on a daemon thread and return
        immediately (Spark's ``writeStream.start()``); pair with
        :meth:`awaitTermination`/:meth:`stop`.  The engine stays a
        single writer — all batch work happens on the one loop thread;
        ``stop()`` flips the flag, JOINS the loop thread, and only then
        closes the append-WAL handles (never under the loop's feet)."""
        import threading

        if getattr(self, "_thread", None) is not None and self._thread.is_alive():
            raise RuntimeError("query already started")
        if self._stopped:
            raise RuntimeError("query was stopped; construct a new one")

        def _loop():
            try:
                self.run(poll_interval=poll_interval)
            except BaseException as e:  # surfaced by awaitTermination
                self._exception = e

        self._exception: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=_loop, name="sntc-streaming-query", daemon=True
        )
        self._thread.start()
        return self

    @property
    def isActive(self) -> bool:
        t = getattr(self, "_thread", None)
        return t is not None and t.is_alive()

    @property
    def lastProgress(self) -> Optional[dict]:
        return self.recentProgress[-1] if self.recentProgress else None

    def awaitTermination(self, timeout: Optional[float] = None) -> bool:
        """Block until the query stops (or ``timeout`` seconds pass);
        returns True if it terminated.  Re-raises a crash from the loop
        thread, as Spark's ``awaitTermination`` does."""
        t = getattr(self, "_thread", None)
        if t is not None:
            t.join(timeout)
            if not t.is_alive() and self._exception is not None:
                raise self._exception
            return not t.is_alive()
        return self._stopped

    def stop(self) -> None:
        was_active = self.isActive
        self._stopped = True
        try:
            if was_active:
                # the loop thread still uses the WAL handles; wait for it
                # to exit its current batch before closing them
                self._thread.join()
                if self._exception is not None:
                    raise self._exception
        finally:
            if self.wal_mode == "append":
                self._offsets_log.close()
                self._commits_log.close()
            if self._delivery_pool is not None:
                # a still-running delivery finishes (its settle never
                # happens: the batch stays uncommitted in the WAL and a
                # restarted query replays it — the crash contract)
                self._delivery_pool.shutdown(wait=True)
                self._delivery_pool = None
                self._delivery = None
