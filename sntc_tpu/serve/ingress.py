"""Live network front door (r20): socket ingress that WALs at the edge.

UDP datagrams are inherently lossy and non-replayable, so the serving
plane never processes them directly.  Instead the listeners here follow
Spark's reliable-receiver pattern — *persist first, then process from
the log*: every datagram/frame lands in a bounded in-memory ring, a
spooler thread seals ring contents into capture files atomically (fsync
file + containing dir around the rename — the PR-12 discipline), and the
engine replays the sealed files through the ordinary directory sources.
WAL replay, admission, flow keying, the ingest autotuner, and the SLO
controller all compose unchanged because the spool IS a source
directory.

The loss-accounting law
-----------------------
Nothing is ever dropped silently.  Every payload that reaches the
receive boundary is either (a) sealed into a capture file, (b) still in
flight (ring/seal buffer — zero after :meth:`drain`), or (c) counted in
``sntc_ingress_dropped_total{reason}`` and the durable
``ingress_stats.json``.  After a drain::

    received == spooled + sum(dropped.values())

holds exactly — the conservation law the chaos harness asserts.

The backpressure ladder
-----------------------
1. **TCP pauses reads** while the spool exceeds its byte budget
   (``sntc_ingress_backpressure_state`` = 1); kernel TCP flow control
   pushes back to the sender, resuming below ~80% of budget.
2. **UDP ring overflow is counted shed** (``reason="ring_overflow"``):
   the ring bounds memory, the counter keeps the law.
3. **Disk budget breach sheds at ingress** (``reason=
   "spool_over_budget"``) after a committed-file prune attempt —
   bounded disk instead of ENOSPC death (the spool artifact's SHED
   policy).

Fault sites: ``ingress.recv`` guards the receive boundary (DATA kinds
corrupt the payload there, exactly like ``source.parse``);
``ingress.spool`` guards the seal (IO kinds + ``kill`` — the
kill-mid-spool chaos scenario).  A kill between a sender's send and the
seal rename loses nothing the sender still holds: the atomic rename is
the ack, so resend-until-sealed gives exactly-once into the spool.
"""

from __future__ import annotations

import glob
import json
import os
import re
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from sntc_tpu.obs.metrics import inc, set_gauge
from sntc_tpu.resilience.faults import fault_data, fault_point
from sntc_tpu.resilience.policy import emit_event
from sntc_tpu.resilience.storage import atomic_write_bytes, write_marker
from sntc_tpu.serve.netflow_source import NetFlowDirSource
from sntc_tpu.serve.streaming import FileStreamSource

STATS_FILE = "ingress_stats.json"
QUARANTINE_DIR = "quarantine"

#: TCP framing: 4-byte big-endian payload length, then the payload (one
#: utf-8 CSV row, no trailing newline).
FRAME_HEADER = struct.Struct(">I")

_IDX_RE = re.compile(r"(\d+)")


def _file_index(path: str) -> int:
    """The monotonic sequence index encoded in a spool file name
    (``capture_000123.nf5`` -> 123)."""
    m = _IDX_RE.search(os.path.basename(path))
    if m is None:
        raise ValueError(f"spool file without sequence index: {path!r}")
    return int(m.group(1))


def _labels(tenant: Optional[str]) -> Dict[str, str]:
    return {} if tenant is None else {"tenant": tenant}


class IngressStats:
    """Thread-safe ingress accounting — the in-memory side of the
    conservation law.  Mirrored durably into ``ingress_stats.json`` at
    every seal/prune/drain, so harnesses (and operators) can audit the
    law across process death."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.received = 0
        self.received_bytes = 0
        self.spooled = 0
        self.sealed_files = 0
        self.pruned_files = 0
        self.quarantined = 0
        self.dropped: Dict[str, int] = {}
        self.drained = False

    def note_received(self, nbytes: int) -> None:
        with self._lock:
            self.received += 1
            self.received_bytes += nbytes

    def note_spooled(self, units: int) -> None:
        with self._lock:
            self.spooled += units
            self.sealed_files += 1

    def note_dropped(self, reason: str, units: int = 1) -> None:
        with self._lock:
            self.dropped[reason] = self.dropped.get(reason, 0) + units

    def note_pruned(self, files: int) -> None:
        with self._lock:
            self.pruned_files += files

    def note_quarantined(self) -> None:
        with self._lock:
            self.quarantined += 1

    def dropped_total(self) -> int:
        with self._lock:
            return sum(self.dropped.values())

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "received": self.received,
                "received_bytes": self.received_bytes,
                "spooled": self.spooled,
                "sealed_files": self.sealed_files,
                "pruned_files": self.pruned_files,
                "quarantined": self.quarantined,
                "dropped": dict(self.dropped),
                "drained": self.drained,
            }


class IngressSpool:
    """The durable, replayable ingress WAL: a directory of sealed
    capture files with monotonic sequence names, keep-N retention of
    COMMITTED files, and a disk-budget shed valve.

    Sequence names are derived from max-existing-index + 1 (never
    ``len(glob(...))`` — a pruned spool would reuse indices and
    silently overwrite live captures), so the name order IS the offset
    order and the numeric index IS the source offset: file ``i`` sits
    at listing position ``i`` once the pruned prefix is tombstoned
    (:class:`_SpoolOffsetMixin`).

    Retention only ever prunes files whose index is strictly below the
    engine's committed horizon (``committed_offset_fn``, wired to
    ``StreamingQuery.committed_end``): a file the engine has not
    committed past is never deleted, so replay after a crash always
    finds every uncommitted byte."""

    def __init__(
        self,
        spool_dir: str,
        *,
        prefix: str = "capture_",
        suffix: str = ".nf5",
        tenant: Optional[str] = None,
        keep_files: int = 64,
        spool_budget_mb: Optional[float] = None,
        committed_offset_fn: Optional[Callable[[], int]] = None,
    ) -> None:
        self.spool_dir = spool_dir
        self.prefix = prefix
        self.suffix = suffix
        self.tenant = tenant
        self.keep_files = max(1, int(keep_files))
        self.budget_bytes = (
            int(spool_budget_mb * (1 << 20)) if spool_budget_mb else None
        )
        self.committed_offset_fn = committed_offset_fn
        self.stats = IngressStats()
        self._lock = threading.RLock()
        # the durable stats file is accounting, not the WAL: throttle
        # its fsync off the hot seal path.  Exception: a prune MUST
        # write through, because index resume after a restart falls
        # back to stats only when pruning has removed the live files
        # that would otherwise witness the true max index.
        self._stats_written_at = 0.0
        self.stats_interval_s = 0.25
        os.makedirs(spool_dir, exist_ok=True)
        live = self._live_files()
        self._next_idx = (_file_index(live[-1]) + 1) if live else 0
        prior = self.read_stats(spool_dir)
        if prior:
            # a restart resumes the sequence past everything ever
            # sealed, even when retention has since pruned it all
            self._next_idx = max(
                self._next_idx, int(prior.get("sealed_files", 0))
            )
            self.stats.pruned_files = int(prior.get("pruned_files", 0))

    # -- introspection -------------------------------------------------------

    @staticmethod
    def read_stats(spool_dir: str) -> Optional[Dict[str, Any]]:
        try:
            with open(os.path.join(spool_dir, STATS_FILE)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _live_files(self) -> List[str]:
        return sorted(
            glob.glob(
                os.path.join(self.spool_dir, self.prefix + "*" + self.suffix)
            )
        )

    def spool_bytes(self) -> int:
        total = 0
        for p in self._live_files():
            try:
                total += os.path.getsize(p)
            except OSError:
                pass
        return total

    def over_budget(self, headroom: float = 1.0) -> bool:
        if self.budget_bytes is None:
            return False
        return self.spool_bytes() > self.budget_bytes * headroom

    # -- the seal (the WAL append) -------------------------------------------

    def seal(self, payload: bytes, units: int, extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Atomically publish one capture file holding ``units``
        payloads.  Returns the sealed path, or None when the payload
        was SHED (budget) or lost to an injected/real IO fault — in
        both cases the loss is counted, never silent."""
        with self._lock:
            if self.budget_bytes is not None:
                projected = self.spool_bytes() + len(payload)
                if projected > self.budget_bytes:
                    # a budget prune may remove EVERY committed witness
                    # file: write the stats through before the seal's
                    # fault boundary, or a kill in the throttle window
                    # resumes at a stale next_idx and reuses sealed
                    # indices below the committed horizon (r23 bugfix)
                    if self._prune(
                        budget_target=self.budget_bytes - len(payload)
                    ):
                        self._write_stats()
                    projected = self.spool_bytes() + len(payload)
                if projected > self.budget_bytes:
                    self.stats.note_dropped("spool_over_budget", units)
                    inc(
                        "sntc_ingress_dropped_total", units,
                        reason="spool_over_budget", **_labels(self.tenant),
                    )
                    emit_event(
                        event="ingress_shed", reason="spool_over_budget",
                        units=units, bytes=len(payload),
                        budget_bytes=self.budget_bytes,
                        tenant=self.tenant,
                    )
                    self._write_stats()
                    return None
            path = os.path.join(
                self.spool_dir,
                f"{self.prefix}{self._next_idx:06d}{self.suffix}",
            )
            try:
                # the kill-mid-spool chaos boundary: a kill here leaves
                # no sealed file, so a resend-until-sealed sender loses
                # nothing; IO kinds model the full/failing disk
                fault_point("ingress.spool", tenant=self.tenant)
                atomic_write_bytes(
                    path, payload, site="ingress.spool", tenant=self.tenant
                )
            except Exception as e:
                # the artifact's SHED policy: a failing spool disk sheds
                # at ingress (counted) instead of killing the listener
                self.stats.note_dropped("spool_error", units)
                inc(
                    "sntc_ingress_dropped_total", units,
                    reason="spool_error", **_labels(self.tenant),
                )
                emit_event(
                    event="ingress_shed", reason="spool_error",
                    units=units, error=repr(e), tenant=self.tenant,
                )
                self._write_stats()
                return None
            self._next_idx += 1
            self.stats.note_spooled(units)
            inc(
                "sntc_ingress_sealed_files_total", 1,
                **_labels(self.tenant),
            )
            set_gauge(
                "sntc_ingress_spool_bytes", self.spool_bytes(),
                **_labels(self.tenant),
            )
            pruned = self._prune()
            # a seal landing within one file of the retention horizon
            # is immediately prunable: its stats write must not wait
            # out the throttle window, or a kill inside it leaves no
            # witness — neither a live file nor current stats — of the
            # sealed index (r23 bugfix)
            near_horizon = False
            if self.committed_offset_fn is not None:
                try:
                    near_horizon = (
                        self._next_idx - int(self.committed_offset_fn())
                        <= 2
                    )
                except Exception:
                    near_horizon = False
            if (
                pruned
                or near_horizon
                or time.monotonic() - self._stats_written_at
                >= self.stats_interval_s
            ):
                self._write_stats(extra)
            return path

    def quarantine(self, data: bytes, reason: str) -> Optional[str]:
        """Preserve undecodable evidence (a torn TCP frame) under
        ``quarantine/`` — dropped from the stream (counted) but never
        destroyed."""
        qdir = os.path.join(self.spool_dir, QUARANTINE_DIR)
        n = self.stats.quarantined
        path = os.path.join(qdir, f"{reason}_{os.getpid()}_{n:06d}.bin")
        try:
            atomic_write_bytes(
                path, data, site="ingress.spool", tenant=self.tenant
            )
        except Exception:
            path = None
        self.stats.note_quarantined()
        return path

    # -- retention (keep-N committed + budget shed) --------------------------

    def _prune(self, budget_target: Optional[int] = None) -> int:
        """Prune COMMITTED capture files: oldest-first, only files the
        engine has committed past, down to ``keep_files`` retained
        committed files (or ``budget_target`` bytes when given).
        Without a committed-offset feed nothing is pruned — bounding
        falls to the budget shed valve, which drops NEW payloads
        instead of replayable history."""
        if self.committed_offset_fn is None:
            return 0
        try:
            horizon = int(self.committed_offset_fn())
        except Exception:
            return 0
        live = self._live_files()
        committed = [p for p in live if _file_index(p) < horizon]
        if budget_target is None:
            drop = (
                committed[: -self.keep_files]
                if len(committed) > self.keep_files else []
            )
        else:
            drop, total = [], self.spool_bytes()
            for p in committed:
                if total <= budget_target:
                    break
                try:
                    total -= os.path.getsize(p)
                except OSError:
                    pass
                drop.append(p)
        pruned = 0
        for p in drop:
            try:
                os.unlink(p)
                pruned += 1
            except OSError:
                pass
        if pruned:
            self.stats.note_pruned(pruned)
            inc(
                "sntc_ingress_pruned_files_total", pruned,
                **_labels(self.tenant),
            )
            emit_event(
                event="ingress_pruned", files=pruned, horizon=horizon,
                tenant=self.tenant,
            )
        return pruned

    # -- durable accounting --------------------------------------------------

    def _write_stats(self, extra: Optional[Dict[str, Any]] = None) -> None:
        obj = self.stats.snapshot()
        obj["next_idx"] = self._next_idx
        if extra:
            obj.update(extra)
        write_marker(
            os.path.join(self.spool_dir, STATS_FILE), obj,
            tenant=self.tenant,
        )
        self._stats_written_at = time.monotonic()

    def publish_stats(self, **extra: Any) -> None:
        with self._lock:
            self._write_stats(extra or None)


def _recv_boundary(data: bytes, tenant: Optional[str]) -> bytes:
    """The shared receive-boundary fault hook: ``ingress.recv`` takes
    exception kinds (a failing NIC/driver read) AND the DATA kinds
    (corrupt/truncated datagrams — downstream parse salvage must hold
    over network input exactly as over disk input)."""
    fault_point("ingress.recv", tenant=tenant)
    return fault_data("ingress.recv", data)


class _ListenerBase:
    """Shared ring + spooler machinery of both listeners: payloads
    enter through :meth:`_ingest` (socket threads or tests), a spooler
    thread groups and seals them, :meth:`drain` stops intake and seals
    the tail, :meth:`close` tears down."""

    def __init__(
        self,
        spool: IngressSpool,
        *,
        ring_size: int,
        seal_units: int,
        seal_idle_s: float,
        tenant: Optional[str],
    ) -> None:
        self.spool = spool
        self.stats = spool.stats
        self.tenant = tenant
        self.ring_size = max(1, int(ring_size))
        self.seal_units = max(1, int(seal_units))
        self.seal_idle_s = float(seal_idle_s)
        self._ring: List[bytes] = []
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._discard = False
        self._threads: List[threading.Thread] = []
        self._started = False

    # -- intake --------------------------------------------------------------

    def _ingest(self, data: bytes) -> None:
        """One payload past the receive boundary and into the ring —
        the unit the conservation law counts."""
        data = _recv_boundary(data, self.tenant)
        self.stats.note_received(len(data))
        inc(self._recv_metric, 1, **_labels(self.tenant))
        inc("sntc_ingress_bytes_total", len(data), **_labels(self.tenant))
        with self._cv:
            if len(self._ring) >= self.ring_size:
                # the UDP rung of the backpressure ladder: bounded
                # memory, counted shed — never silent loss
                self.stats.note_dropped("ring_overflow", 1)
                inc(
                    "sntc_ingress_dropped_total", 1,
                    reason="ring_overflow", **_labels(self.tenant),
                )
            else:
                self._ring.append(data)
                self._cv.notify()
            set_gauge(
                "sntc_ingress_ring_depth", len(self._ring),
                **_labels(self.tenant),
            )

    # -- the spooler thread --------------------------------------------------

    def _spool_loop(self) -> None:
        buf: List[bytes] = []
        last_activity = time.monotonic()
        while True:
            moved = 0
            with self._cv:
                if not self._ring and not self._stop.is_set():
                    self._cv.wait(timeout=max(0.02, self.seal_idle_s / 4))
                while self._ring and len(buf) < self.seal_units:
                    buf.append(self._ring.pop(0))
                    moved += 1
                ring_empty = not self._ring
                set_gauge(
                    "sntc_ingress_ring_depth", len(self._ring),
                    **_labels(self.tenant),
                )
            if moved:
                # the idle clock restarts only on ARRIVALS — a partial
                # group merely sitting in buf must age toward the tail
                # seal, not refresh itself every wakeup
                last_activity = time.monotonic()
            stopping = self._stop.is_set()
            if self._discard:
                if buf:
                    self.stats.note_dropped("close_discard", len(buf))
                    inc(
                        "sntc_ingress_dropped_total", len(buf),
                        reason="close_discard", **_labels(self.tenant),
                    )
                    buf = []
                if stopping and ring_empty:
                    return
                continue
            if len(buf) >= self.seal_units:
                self._seal(buf)
                buf = []
            elif buf and (
                stopping
                or time.monotonic() - last_activity >= self.seal_idle_s
            ):
                # tail seal: a drain (or an idle gap) must not strand
                # a partial group in memory
                self._seal(buf)
                buf = []
            if stopping and ring_empty and not buf:
                return

    def _seal(self, buf: List[bytes]) -> None:
        raise NotImplementedError

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        if self._started:
            return self
        self._started = True
        t = threading.Thread(
            target=self._spool_loop, name="sntc-ingress-spool", daemon=True
        )
        t.start()
        self._threads.append(t)
        self._start_io_threads()
        self.spool.publish_stats(**self._endpoint())
        return self

    def _start_io_threads(self) -> None:
        pass

    def _endpoint(self) -> Dict[str, Any]:
        return {}

    def drain(self, timeout_s: float = 30.0) -> Dict[str, Any]:
        """Graceful stop: no new intake, ring + tail sealed, stats
        published with ``drained=true``.  After this the conservation
        law holds exactly: received == spooled + dropped."""
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        deadline = time.monotonic() + timeout_s
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        self.stats.drained = True
        self.spool.publish_stats(**self._endpoint())
        emit_event(
            event="ingress_drained", tenant=self.tenant,
            **self.stats.snapshot(),
        )
        return self.stats.snapshot()

    def close(self) -> None:
        """Hard stop: pending ring contents are DISCARDED — but
        counted (``reason="close_discard"``), keeping the law."""
        if not self._stop.is_set():
            self._discard = True
        self.drain(timeout_s=5.0)


class UdpIngressListener(_ListenerBase):
    """Supervised UDP ingress: a receiver thread drains NetFlow v5
    datagrams into the bounded ring, the spooler seals
    ``seal_datagrams`` of them per capture file (concatenated datagrams
    — exactly the on-disk shape ``NetFlowDirSource`` replays).  Binding
    ``port=0`` picks an ephemeral port, published in
    ``ingress_stats.json`` (``port``) for harnesses."""

    _recv_metric = "sntc_ingress_datagrams_total"

    def __init__(
        self,
        spool: IngressSpool,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        sock: Optional[socket.socket] = None,
        ring_datagrams: int = 2048,
        seal_datagrams: int = 30,
        seal_idle_s: float = 0.25,
        recv_timeout_s: float = 0.2,
        tenant: Optional[str] = None,
    ) -> None:
        super().__init__(
            spool, ring_size=ring_datagrams, seal_units=seal_datagrams,
            seal_idle_s=seal_idle_s, tenant=tenant,
        )
        self._own_sock = sock is None
        if sock is None:
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                # NetFlow exporters burst; the default ~200 KiB kernel
                # buffer holds only a handful of full datagrams.  Ask
                # for 4 MiB (the kernel caps at net.core.rmem_max) so
                # bursts land in OUR counted ring, not in an uncounted
                # kernel drop.
                sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_RCVBUF, 1 << 22
                )
            except OSError:
                pass
            sock.bind((host, port))
        sock.settimeout(recv_timeout_s)
        self.sock = sock
        self.host, self.port = sock.getsockname()[:2]

    def _endpoint(self) -> Dict[str, Any]:
        return {"port": self.port, "proto": "udp"}

    def _start_io_threads(self) -> None:
        t = threading.Thread(
            target=self._rx_loop, name="sntc-ingress-udp", daemon=True
        )
        t.start()
        self._threads.append(t)

    def _rx_loop(self) -> None:
        while not self._stop.is_set():
            try:
                data, _ = self.sock.recvfrom(65_535)
            except socket.timeout:
                continue
            except OSError:
                break  # socket closed under us: a drain/close is in flight
            try:
                self._ingest(data)
            except Exception as e:
                # an injected (or real) receive failure drops ONE
                # datagram, counted — it must not kill the listener.
                # The corrupt arrival still counts as received, so the
                # conservation law stays an equality.
                self.stats.note_received(len(data))
                self.stats.note_dropped("recv_error", 1)
                inc(
                    "sntc_ingress_dropped_total", 1,
                    reason="recv_error", **_labels(self.tenant),
                )
                emit_event(
                    event="ingress_recv_error", error=repr(e),
                    tenant=self.tenant,
                )
        if self._own_sock:
            try:
                self.sock.close()
            except OSError:
                pass

    def _seal(self, buf: List[bytes]) -> None:
        self.spool.seal(b"".join(buf), units=len(buf), extra=self._endpoint())


class TcpRowIngress(_ListenerBase):
    """Framed TCP row ingest — the "millions of clients" shape: each
    connection sends length-prefixed utf-8 CSV rows (4-byte big-endian
    length, then the row).  Rows seal into ``rows_NNNNNN.csv`` files
    (header + rows) that ``FileStreamSource``/``CsvSpoolSource``
    replay.

    Per-connection framing is independent: a client that dies
    mid-frame quarantines its torn tail (``quarantine/``, counted
    ``torn_frame``) without touching any other connection.  While the
    spool is over budget the reader threads PAUSE between frames —
    kernel TCP flow control turns that pause into sender backpressure
    (``sntc_ingress_backpressure_state`` = 1)."""

    _recv_metric = "sntc_ingress_frames_total"

    def __init__(
        self,
        spool: IngressSpool,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        sock: Optional[socket.socket] = None,
        columns: Optional[List[str]] = None,
        ring_frames: int = 4096,
        seal_rows: int = 256,
        seal_idle_s: float = 0.25,
        max_frame_bytes: int = 1 << 20,
        accept_timeout_s: float = 0.2,
        tenant: Optional[str] = None,
    ) -> None:
        super().__init__(
            spool, ring_size=ring_frames, seal_units=seal_rows,
            seal_idle_s=seal_idle_s, tenant=tenant,
        )
        self.columns = list(columns) if columns else None
        self.max_frame_bytes = int(max_frame_bytes)
        self._own_sock = sock is None
        if sock is None:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((host, port))
        sock.listen(32)
        sock.settimeout(accept_timeout_s)
        self.sock = sock
        self.host, self.port = sock.getsockname()[:2]
        self._conns = 0
        self._conn_lock = threading.Lock()

    def _endpoint(self) -> Dict[str, Any]:
        return {"tcp_port": self.port, "proto": "tcp"}

    def _start_io_threads(self) -> None:
        t = threading.Thread(
            target=self._accept_loop, name="sntc-ingress-tcp", daemon=True
        )
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        handlers: List[threading.Thread] = []
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            h = threading.Thread(
                target=self._conn_loop, args=(conn,),
                name="sntc-ingress-conn", daemon=True,
            )
            h.start()
            handlers.append(h)
        if self._own_sock:
            try:
                self.sock.close()
            except OSError:
                pass
        # a drain waits for in-flight connections to settle (each
        # reader exits at its next frame boundary once _stop is set)
        for h in handlers:
            h.join(timeout=5.0)

    def _conn_gauge(self, delta: int) -> None:
        with self._conn_lock:
            self._conns += delta
            set_gauge(
                "sntc_ingress_connections", self._conns,
                **_labels(self.tenant),
            )

    def _recv_exact(self, conn: socket.socket, n: int) -> bytes:
        """Read exactly ``n`` bytes; returns the SHORT prefix when the
        peer closes mid-read (the torn-frame evidence)."""
        chunks = []
        got = 0
        while got < n and not self._stop.is_set():
            try:
                chunk = conn.recv(min(65_536, n - got))
            except socket.timeout:
                continue
            except OSError:
                break
            if not chunk:
                break
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    def _conn_loop(self, conn: socket.socket) -> None:
        conn.settimeout(0.2)
        self._conn_gauge(+1)
        try:
            while not self._stop.is_set():
                # rung 1 of the backpressure ladder: stop reading while
                # the spool is over budget; resume below 80% of it
                if self.spool.over_budget():
                    set_gauge(
                        "sntc_ingress_backpressure_state", 1,
                        **_labels(self.tenant),
                    )
                    while (
                        self.spool.over_budget(headroom=0.8)
                        and not self._stop.is_set()
                    ):
                        time.sleep(0.02)
                    set_gauge(
                        "sntc_ingress_backpressure_state", 0,
                        **_labels(self.tenant),
                    )
                header = self._recv_exact(conn, FRAME_HEADER.size)
                if not header:
                    break  # clean close at a frame boundary
                if len(header) < FRAME_HEADER.size:
                    self._torn(header)
                    break
                (length,) = FRAME_HEADER.unpack(header)
                if length > self.max_frame_bytes:
                    # an unframeable stream cannot be resynced: drop
                    # the frame, close the connection (the arrival is
                    # still counted received — the law is an equality)
                    self.stats.note_received(len(header))
                    self.stats.note_dropped("oversize_frame", 1)
                    inc(
                        "sntc_ingress_dropped_total", 1,
                        reason="oversize_frame", **_labels(self.tenant),
                    )
                    break
                payload = self._recv_exact(conn, length)
                if len(payload) < length:
                    self._torn(header + payload)
                    break
                try:
                    self._ingest(payload)
                except Exception as e:
                    self.stats.note_received(len(payload))
                    self.stats.note_dropped("recv_error", 1)
                    inc(
                        "sntc_ingress_dropped_total", 1,
                        reason="recv_error", **_labels(self.tenant),
                    )
                    emit_event(
                        event="ingress_recv_error", error=repr(e),
                        tenant=self.tenant,
                    )
        finally:
            try:
                conn.close()
            except OSError:
                pass
            self._conn_gauge(-1)

    def _torn(self, partial: bytes) -> None:
        self.spool.quarantine(partial, "torn_frame")
        # the torn bytes DID arrive: received counts them so the
        # conservation law (received == spooled + dropped) stays exact
        self.stats.note_received(len(partial))
        self.stats.note_dropped("torn_frame", 1)
        inc(
            "sntc_ingress_dropped_total", 1,
            reason="torn_frame", **_labels(self.tenant),
        )
        emit_event(
            event="ingress_torn_frame", bytes=len(partial),
            tenant=self.tenant,
        )

    def _seal(self, buf: List[bytes]) -> None:
        lines: List[str] = []
        if self.columns:
            lines.append(",".join(self.columns))
        lines.extend(b.decode("utf-8", "replace") for b in buf)
        payload = ("\n".join(lines) + "\n").encode()
        self.spool.seal(payload, units=len(buf), extra=self._endpoint())


# ---------------------------------------------------------------------------
# replayable sources over a pruned spool (tombstone offsets)
# ---------------------------------------------------------------------------

#: listing placeholder for a retention-pruned capture file — it holds
#: the file's OFFSET position so pruning never renumbers live files
#: (renumbering would silently replay or skip under the engine's WAL)
PRUNED = "<pruned>"


class _SpoolOffsetMixin:
    """Directory-source mixin that keeps source offsets STABLE across
    spool retention: offset ``i`` is capture file index ``i`` forever.
    The listing is the live files left-padded with :data:`PRUNED`
    tombstones — one per pruned predecessor, derived from the first
    live file's sequence index (pruning is oldest-first and names are
    contiguous from 0, so the first live index IS the pruned count;
    with an empty spool the durable ``ingress_stats.json`` carries the
    horizon across restarts).  Reading a tombstoned offset raises —
    retention only prunes below the committed horizon, so a planned
    batch can only hit one if the WAL was deleted out from under the
    spool."""

    def _scan(self) -> List[str]:
        real = sorted(glob.glob(os.path.join(self.path, self.pattern)))
        if real:
            floor = _file_index(real[0])
        else:
            stats = IngressSpool.read_stats(self.path)
            floor = int(stats.get("pruned_files", 0)) if stats else 0
        prior = getattr(self, "_floor", 0)
        self._floor = max(floor, prior)
        return [PRUNED] * self._floor + real

    def _files(self) -> List[str]:
        self._listing = self._scan()
        return self._listing

    def files_for_range(self, start: int, end: int) -> List[str]:
        listing = self._listing
        if listing is None or len(listing) < end:
            listing = self._scan()
        return [f for f in listing[start:end] if f is not PRUNED]

    def _read_range(self, start, end, listing):
        if listing is None or len(listing) < end:
            listing = self._scan()
        files = listing[start:end]
        if any(f is PRUNED for f in files):
            raise ValueError(
                f"batch range [{start}, {end}) is below the spool "
                "retention horizon (pruned capture files) — the "
                "offset WAL does not match this spool"
            )
        return super()._read_range(start, end, listing)

    # -- listener attachment (daemon/serve lifecycle hooks) ------------------

    def attach_listener(self, listener) -> None:
        self._listeners = getattr(self, "_listeners", [])
        self._listeners.append(listener)

    def drain_ingress(self) -> None:
        """Settle the attached listeners BEFORE the engine drains, so
        tail datagrams seal in time to be served by the final batches."""
        for l in getattr(self, "_listeners", []):
            try:
                l.drain()
            except Exception:
                pass

    def close(self) -> None:
        for l in getattr(self, "_listeners", []):
            try:
                l.close()
            except Exception:
                pass
        super().close()


class NetFlowSpoolSource(_SpoolOffsetMixin, NetFlowDirSource):
    """NetFlow capture source over a retention-pruned ingress spool."""

    def __init__(self, path: str, pattern: str = "capture_*.nf5", **kwargs):
        super().__init__(path, pattern, **kwargs)


class CsvSpoolSource(_SpoolOffsetMixin, FileStreamSource):
    """CSV row source over a retention-pruned ingress spool."""

    def __init__(self, path: str, pattern: str = "rows_*.csv", **kwargs):
        super().__init__(path, pattern, **kwargs)


# ---------------------------------------------------------------------------
# client-side framing + wiring helpers
# ---------------------------------------------------------------------------


def frame_rows(rows: List[str]) -> bytes:
    """Length-prefix ``rows`` for :class:`TcpRowIngress` (the client
    half of the framing contract)."""
    return b"".join(
        FRAME_HEADER.pack(len(r)) + r
        for r in (row.encode() for row in rows)
    )


def build_ingress(
    spool_dir: str,
    *,
    listen_udp: Optional[int] = None,
    listen_tcp: Optional[int] = None,
    spool_mb: Optional[float] = None,
    keep_files: int = 64,
    ring: int = 2048,
    seal_every: int = 30,
    seal_idle_s: float = 0.25,
    columns: Optional[List[str]] = None,
    tenant: Optional[str] = None,
    source_kwargs: Optional[Dict[str, Any]] = None,
) -> Tuple[Any, List[Any]]:
    """Build (source, listeners) for one ingress endpoint: the spool
    directory doubles as the source's watch directory, the listeners
    are attached to the source (so source drain/close settles them),
    and the spool's retention horizon is wired to the engine by the
    caller via ``wire_committed_offset``."""
    if (listen_udp is None) == (listen_tcp is None):
        raise ValueError(
            "exactly one of listen_udp / listen_tcp must be given "
            "(one spool directory holds one capture format)"
        )
    kwargs = dict(source_kwargs or {})
    kwargs.setdefault("tenant", tenant)
    if listen_udp is not None:
        spool = IngressSpool(
            spool_dir, prefix="capture_", suffix=".nf5", tenant=tenant,
            keep_files=keep_files, spool_budget_mb=spool_mb,
        )
        listener = UdpIngressListener(
            spool, port=listen_udp, ring_datagrams=ring,
            seal_datagrams=seal_every, seal_idle_s=seal_idle_s,
            tenant=tenant,
        )
        source = NetFlowSpoolSource(spool_dir, **kwargs)
    else:
        spool = IngressSpool(
            spool_dir, prefix="rows_", suffix=".csv", tenant=tenant,
            keep_files=keep_files, spool_budget_mb=spool_mb,
        )
        listener = TcpRowIngress(
            spool, port=listen_tcp, ring_frames=ring,
            seal_rows=seal_every, seal_idle_s=seal_idle_s,
            columns=columns, tenant=tenant,
        )
        source = CsvSpoolSource(spool_dir, **kwargs)
    source.attach_listener(listener)
    source.spool = spool
    return source, [listener]


def wire_committed_offset(source, fn: Callable[[], int]) -> None:
    """Feed the engine's committed horizon into the spool's retention
    (call once the ``StreamingQuery`` exists:
    ``wire_committed_offset(src, query.committed_end)``)."""
    spool = getattr(source, "spool", None)
    if spool is not None:
        spool.committed_offset_fn = fn
