"""Closed-loop SLO controller for the serve plane (r16).

PR 8 made every serving knob observable and PR 10 proved the
control-loop idiom (feedback signal → hysteresis-guarded single-knob
step → journaled decision → provable no-oscillation bound) on the
ingest graph.  :class:`ServeController` closes the remaining loop: the
serving plane's own knobs — pipeline depth, shape-bucket floors, DRR
weights, rate quotas, shed policies — stop being frozen CLI-flag
values and steer themselves toward the per-tenant SLOs declared on
:class:`~sntc_tpu.serve.tenancy.TenantSpec` (``slo_p99_ms``,
``slo_min_rows_per_sec``, ``slo_max_shed_rate``).

**The loop.**  Ticked at daemon-tick cadence, the controller closes an
observation window every ``interval_ticks`` ticks.  Per window it
diffs the :class:`~sntc_tpu.obs.metrics.MetricsRegistry` — per-tenant
committed batches/rows, the ``sntc_batch_duration_seconds`` histogram
buckets (→ windowed p50/p99 via :func:`window_percentile`), shed
offsets, ladder strikes — plus the engine-local backlog, compile
ledger, and breaker states, into one :class:`SloSignal` per tenant;
diagnoses the binding constraint; and moves EXACTLY ONE knob one step
through the shared :class:`~sntc_tpu.resilience.control.Guardrails`
(confirm-streak, post-apply cooldown, per-knob direction-reversal
freeze), so the analytic no-oscillation bound
``Σ_knobs (max_reversals + 1) × (hi − lo)`` holds over the union of
serving + ingest knobs.

**The priority ladder.**  SLO-compliant tenants are protected first:
their knobs are never touched on a neighbor's behalf.  A violator that
is *flooding* (shed-rate violation, or fresh ladder strikes) is
degraded — never its neighbors — down an explicit ladder: tighten its
rate ``quota`` → tighten its ``shed`` cap/policy → ``escalate`` (a
journaled ladder strike; the existing OK → THROTTLED → QUARANTINED →
STOPPED machinery owns what happens next).  A violator that is merely
under-served gets local remedies: latency violations lower its
``pipeline_depth`` (queue wait is latency) or raise its
``shape_buckets`` floor (compile churn is latency); throughput
violations delegate to the PR-10 :class:`~sntc_tpu.data.autotune
.IngestAutotuner` the controller OWNS for its ingest knobs, then
deepen the pipeline, then — only while every other tenant is
compliant — raise its DRR ``weight``.  With no violations the
controller relaxes one previously-degraded knob per window back
toward its cold default, under the same guardrails.

**Evidence.**  Every applied / budget-denied / frozen / delegated /
escalated decision is journaled to ``controller.jsonl`` (one JSON line
per decision, carrying the triggering signal and the post-decision
knob map), emitted as a ``controller_decision`` event, and mirrored to
the cataloged ``sntc_ctl_*`` metrics.  On construction over an
existing journal the controller writes a ``restart`` record logging
the journal's final knob state against the fresh process's cold
defaults — knobs are process-local, so a crash resets them and the
restart record is the reconciliation (the per-tenant drain markers
record the same final knob state on the graceful path).  Controller
failures degrade (``controller_error`` event), never kill the serving
loop — exactly the lifecycle/autotune tick contract.
"""

from __future__ import annotations

import json
import math
import os
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from sntc_tpu.data.pipeline import Knob
from sntc_tpu.obs.metrics import inc, registry, set_gauge
from sntc_tpu.resilience import emit_event, fault_point
from sntc_tpu.resilience.control import (
    ControlPolicy,
    Guardrails,
    TuningBudget,
)

#: the controller's serving-knob action space (docs/RESILIENCE.md
#: keeps a marker-delimited table; scripts/check_controller_flags.py
#: pins CLI ⇔ TenantSpec ⇔ knob names ⇔ docs in tier-1).  weight /
#: quota / shed / escalate exist only on daemon (multi-tenant)
#: targets; shape_buckets only on single-stream targets (the daemon's
#: predictors are SHARED across tenants, so no one tenant may steer
#: their bucket floor).  migrate / scale_out are the FLEET rungs (r19)
#: above escalate: they exist only when the daemon is wired into an
#: elastic fleet (``daemon.fleet_hook`` set by the fleet worker) and
#: each fires at most once per tenant per daemon lifetime — a request
#: marker the coordinator honors, never a local state change — and,
#: like escalate, they are suppressed while the platform is degraded
#: (moving a tenant cannot fix a device fault).
SERVE_KNOB_NAMES = (
    "pipeline_depth",
    "shape_buckets",
    "weight",
    "quota",
    "shed",
    "escalate",
    "migrate",
    "scale_out",
)

#: the fleet rungs of the degradation ladder (subset of
#: SERVE_KNOB_NAMES); one-way like escalate — never relaxed
FLEET_RUNGS = ("migrate", "scale_out")

#: the TenantSpec SLO fields the controller reads as setpoints
SLO_FIELDS = ("slo_p99_ms", "slo_min_rows_per_sec", "slo_max_shed_rate")

#: shape-bucket floor ladder (single-stream): the knob value is the
#: ladder INDEX; raising it trades padding for fewer distinct compiled
#: shapes when the window saw compile churn
SHAPE_BUCKET_FLOORS = (0, 64, 128, 256, 512)

#: quota ladder (daemon): index 0 = the spec's declared quota (or
#: unlimited); index i > 0 throttles to ``base × factor`` where base
#: is the max of the declared quota and the observed rows/s at first
#: throttle — deterministic once captured, journaled with the decision
QUOTA_FACTORS = (None, 0.5, 0.25, 0.125)

#: shed ladder (daemon): index 0 = the spec's declared cap/policy;
#: tightening lowers the backlog cap and finally switches to the
#: sample policy (coverage at reduced resolution)
SHED_LADDER = (None, (8, "oldest"), (4, "oldest"), (2, "sample"))

#: default serving-knob bounds (ladder knobs are bounded by their
#: ladder length; these bound the plain integer knobs)
SERVE_KNOB_BOUNDS = {
    "pipeline_depth": (1, 4),
    "weight": (1, 8),
}


@dataclass
class SloPolicy:
    """A declared SLO triple (the single-stream analog of the
    TenantSpec fields; 0 normalizes to None exactly like the spec)."""

    slo_p99_ms: Optional[float] = None
    slo_min_rows_per_sec: Optional[float] = None
    slo_max_shed_rate: Optional[float] = None

    def __post_init__(self):
        for f in SLO_FIELDS:
            v = getattr(self, f)
            if v == 0:
                setattr(self, f, None)
            elif v is not None and v < 0:
                raise ValueError(f"{f} must be >= 0 (0/None = unset)")
        if (
            self.slo_max_shed_rate is not None
            and self.slo_max_shed_rate > 1.0
        ):
            # same contract as TenantSpec: a shed-rate "bound" over
            # 1.0 can never be violated — a typo, and it must be loud
            raise ValueError("slo_max_shed_rate is a fraction in (0, 1]")

    @classmethod
    def from_spec(cls, spec) -> "SloPolicy":
        return cls(**{f: getattr(spec, f, None) for f in SLO_FIELDS})

    def declared(self) -> bool:
        return any(getattr(self, f) is not None for f in SLO_FIELDS)

    def as_dict(self) -> Dict[str, Optional[float]]:
        return {f: getattr(self, f) for f in SLO_FIELDS}


@dataclass
class SloSignal:
    """One tenant's observation window, condensed from the registry
    deltas + engine-local state.  Pure data so tests drive
    :meth:`ServeController.step` synthetically."""

    batches: int = 0
    rows: int = 0
    rows_per_s: float = 0.0
    p50_ms: Optional[float] = None
    p99_ms: Optional[float] = None
    shed_offsets: int = 0
    shed_rate: float = 0.0
    strikes: int = 0
    backlog: int = 0
    compile_events: int = 0
    breaker_open: bool = False
    elapsed_s: float = 0.0

    def as_fields(self) -> Dict[str, Any]:
        return {
            "batches": self.batches,
            "rows": self.rows,
            "rows_per_s": round(self.rows_per_s, 1),
            "p50_ms": self.p50_ms,
            "p99_ms": self.p99_ms,
            "shed_offsets": self.shed_offsets,
            "shed_rate": round(self.shed_rate, 3),
            "strikes": self.strikes,
            "backlog": self.backlog,
            "compile_events": self.compile_events,
            "breaker_open": self.breaker_open,
        }


def window_percentile(bounds, counts, q: float) -> Optional[float]:
    """The q-th percentile of a WINDOWED histogram (bucket-count
    deltas), by the upper-bound rule: the smallest bucket bound whose
    cumulative count reaches ``ceil(q/100 × total)``.  Deterministic
    and hand-computable — the oracle tests pin it.  Returns None on an
    empty window and ``inf`` when the rank lands in the +Inf overflow
    bucket (callers substitute the window mean)."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = math.ceil(q / 100.0 * total)
    cum = 0
    for bound, n in zip(bounds, counts):
        cum += n
        if cum >= rank:
            return float(bound)
    return float("inf")


class _Target:
    """One controlled stream: a tenant on the daemon, or the single
    supervised engine.  Holds the knob objects, the previous registry
    sample, and the per-window verdicts."""

    def __init__(self, key, engine, slo, stream=None, supervisor=None):
        self.key = key  # tenant id; None = the single-stream engine
        self.engine = engine
        self.slo = slo
        self.stream = stream  # TenantStream (daemon mode)
        self.supervisor = supervisor  # QuerySupervisor (single-stream)
        self.tuner = None  # controller-owned IngestAutotuner
        self.knobs: Dict[str, Knob] = {}
        self.prev: Optional[dict] = None
        self.prev_ts: Optional[float] = None
        self.prev_compiles: Optional[int] = None
        self.last_signal: Optional[SloSignal] = None
        self.compliance: Dict[str, bool] = {}
        self.hold: Dict[str, Tuple[int, float]] = {}  # sticky violations
        self.quota_base: Optional[float] = None
        self.idle_delegations = 0  # consecutive no-op tuner windows

    def controllable(self) -> bool:
        if self.stream is None:
            return True
        return self.stream.state not in ("QUARANTINED", "STOPPED")


class ServeController:
    """The closed loop (module docstring).  Construct via
    :meth:`for_daemon` / :meth:`for_supervisor`; the owner calls
    :meth:`on_tick` once per scheduling round and treats any exception
    as degradation, never death.  Tests drive :meth:`step` directly
    with synthetic :class:`SloSignal` maps."""

    def __init__(
        self,
        *,
        policy: Optional[ControlPolicy] = None,
        journal_path: Optional[str] = None,
        clock=time.monotonic,
        wall=time.time,
        interval_ticks: int = 1,
        budget: Optional[TuningBudget] = None,
        ingest: bool = True,
        knob_bounds: Optional[dict] = None,
        violation_hold: int = 3,
        device_check=None,
    ):
        self.policy = policy or ControlPolicy()
        self.journal_path = journal_path
        self._journal_writer = None
        self.interval_ticks = max(1, int(interval_ticks))
        self.ingest = bool(ingest)
        self.budget = budget
        self.knob_bounds = dict(SERVE_KNOB_BOUNDS, **(knob_bounds or {}))
        # one-shot evidence (a single shed burst, a strike volley)
        # lands in ONE window but the confirm streak needs several: a
        # fresh violation stays live for this many further windows so
        # bursty evidence can clear the guardrails.  Compliance gauges
        # and status always report the INSTANTANEOUS verdict.
        self.violation_hold = max(0, int(violation_hold))
        self._clock = clock
        self._wall = wall
        # compute-plane awareness (r18): a callable returning True
        # while the shared device serves HOST_DEGRADED.  A platform
        # fault collapses every tenant's throughput at once — the
        # controller keeps steering the local knobs through its
        # existing SLO signal, but it must NOT climb the tenant
        # escalation ladder for it (device-attributed failure is not
        # tenant misbehavior).
        self._device_check = device_check
        self.platform_deferrals = 0
        self._daemon = None
        self.targets: List[_Target] = []
        self._knobs: Dict[str, Knob] = {}  # full name -> Knob
        self._defaults: Dict[str, int] = {}  # full name -> cold value
        self._ticks = 0
        self.delegated_total = 0
        self.escalations_total = 0
        self.fleet_requests_total = 0
        self.guard = Guardrails(
            policy=self.policy,
            budget=budget,
            budget_kind=lambda name: name.rsplit("/", 1)[-1],
            on_journal=self._on_journal,
        )

    # -- construction -------------------------------------------------------

    @classmethod
    def for_daemon(cls, daemon, **kwargs) -> "ServeController":
        """Attach to every tenant of a ``ServeDaemon`` (SLOs from the
        TenantSpec fields).  The journal defaults to
        ``<root>/controller.jsonl``."""
        kwargs.setdefault(
            "journal_path",
            os.path.join(daemon.root_dir, "controller.jsonl"),
        )
        kwargs.setdefault("clock", daemon._clock)
        kwargs.setdefault("budget", daemon.tuning_budget)
        kwargs.setdefault("device_check", daemon.device_degraded)
        ctl = cls(**kwargs)
        ctl._daemon = daemon
        for t in daemon.tenants:
            ctl._attach(_Target(
                t.spec.tenant_id, t.query,
                SloPolicy.from_spec(t.spec), stream=t,
            ))
        ctl._reconcile_journal()
        return ctl

    @classmethod
    def for_supervisor(cls, supervisor, slo: SloPolicy,
                       **kwargs) -> "ServeController":
        """Attach to the one engine a ``QuerySupervisor`` owns.  The
        journal defaults to ``<checkpoint>/controller.jsonl``."""
        kwargs.setdefault(
            "journal_path",
            os.path.join(
                supervisor.query.checkpoint_dir, "controller.jsonl"
            ),
        )
        kwargs.setdefault("clock", supervisor._clock)
        dom = getattr(supervisor.query.predictor, "device_domain", None)
        if dom is not None:
            kwargs.setdefault(
                "device_check", lambda _d=dom: _d.host_degraded
            )
        ctl = cls(**kwargs)
        ctl._attach(_Target(
            None, supervisor.query, slo, supervisor=supervisor,
        ))
        ctl._reconcile_journal()
        return ctl

    def attach_tenant(self, stream) -> None:
        """Attach one LATE tenant (r19: a fleet worker applying a new
        assignment mid-run) exactly like :meth:`for_daemon` attaches
        the initial set — its SLOs come from the spec, its knobs join
        the shared guardrails, and its first window baseline primes
        now."""
        self._attach(_Target(
            stream.spec.tenant_id, stream.query,
            SloPolicy.from_spec(stream.spec), stream=stream,
        ))

    def detach_tenant(self, tenant_id: str) -> bool:
        """Detach one tenant (r19: the symmetric inverse of
        :meth:`attach_tenant`, called from ``ServeDaemon.remove_tenant``):
        drop its target and unregister its knobs, so the loop stops
        sampling the stopped engine, stops evaluating its SLO windows,
        and can never post a fleet request for a tenant another worker
        now owns."""
        for t in list(self.targets):
            if t.stream is not None and t.key == tenant_id:
                self.targets.remove(t)
                for base in t.knobs:
                    full = self._full(t, base)
                    self._knobs.pop(full, None)
                    self._defaults.pop(full, None)
                return True
        return False

    def _full(self, t: _Target, base: str) -> str:
        return base if t.key is None else f"{t.key}/{base}"

    def _split(self, name: str) -> Tuple[Optional[str], str]:
        if "/" in name:
            tid, base = name.rsplit("/", 1)
            return tid, base
        return None, name

    def _fault_wrap(self, setter, tenant):
        """Every live knob setter passes the ``ctl.apply`` fault point
        first — the kill-mid-knob-apply chaos boundary.  The journal
        record lands only AFTER the setter returns, so a kill here
        leaves the journal reflecting exactly the fully-applied
        decisions (the restart record reconciles the rest)."""

        def _set(v):
            fault_point("ctl.apply", tenant=tenant)
            setter(v)

        return _set

    def _shed_knob(self, holder, wrap) -> Knob:
        """The shed-ladder knob over any holder exposing
        ``max_pending_batches``/``shed_policy`` (the supervisor on a
        single stream, the TenantSpec on the daemon): index 0 restores
        the declared cap/policy; tightening applies the ladder rung,
        never loosening past an already-declared cap."""
        orig = (holder.max_pending_batches, holder.shed_policy)
        box = {"i": 0}

        def _set_shed(i, _b=box, _h=holder, _o=orig):
            _b["i"] = int(i)
            if _b["i"] == 0:
                _h.max_pending_batches, _h.shed_policy = _o
                return
            cap, pol = SHED_LADDER[_b["i"]]
            if _o[0] is not None:
                cap = min(cap, _o[0])
            _h.max_pending_batches, _h.shed_policy = cap, pol

        return Knob(
            "shed", lambda _b=box: _b["i"], wrap(_set_shed),
            0, len(SHED_LADDER) - 1,
        )

    def _attach(self, t: _Target) -> None:
        self.targets.append(t)
        eng = t.engine
        wrap = lambda fn: self._fault_wrap(fn, t.key)  # noqa: E731
        kn: Dict[str, Knob] = {}

        lo, hi = self.knob_bounds["pipeline_depth"]

        def _set_depth(n, _e=eng):
            _e.pipeline_depth = max(1, int(n))

        kn["pipeline_depth"] = Knob(
            "pipeline_depth", lambda _e=eng: _e.pipeline_depth,
            wrap(_set_depth), lo, hi,
        )

        if t.stream is None:
            # single-stream: the predictor is this engine's alone, so
            # its bucket floor is steerable (ladder-index knob)
            pred = eng.predictor
            ladder = tuple(sorted(
                set(SHAPE_BUCKET_FLOORS) | {int(pred.bucket_rows)}
            ))
            box = {"i": ladder.index(int(pred.bucket_rows))}

            def _set_buckets(i, _b=box, _l=ladder, _p=pred, _e=eng):
                _b["i"] = int(i)
                _p.bucket_rows = _l[_b["i"]]
                _e.shape_buckets = _l[_b["i"]]

            kn["shape_buckets"] = Knob(
                "shape_buckets", lambda _b=box: _b["i"],
                wrap(_set_buckets), 0, len(ladder) - 1,
            )
            if t.supervisor is not None:
                kn["shed"] = self._shed_knob(t.supervisor, wrap)
        else:
            spec = t.stream.spec
            wlo, whi = self.knob_bounds["weight"]

            def _set_weight(n, _s=spec):
                _s.weight = float(max(1, int(n)))

            kn["weight"] = Knob(
                "weight", lambda _s=spec: int(round(_s.weight)),
                wrap(_set_weight), wlo, whi,
            )

            qbox = {"i": 0}
            qorig = spec.max_rows_per_sec

            def _set_quota(i, _b=qbox, _t=t, _orig=qorig):
                _b["i"] = int(i)
                if _b["i"] == 0:
                    _t.stream.set_rate_quota(_orig)
                    return
                if _t.quota_base is None:
                    observed = (
                        _t.last_signal.rows_per_s
                        if _t.last_signal is not None else 0.0
                    )
                    _t.quota_base = max(_orig or 0.0, observed, 1.0)
                _t.stream.set_rate_quota(
                    _t.quota_base * QUOTA_FACTORS[_b["i"]]
                )

            kn["quota"] = Knob(
                "quota", lambda _b=qbox: _b["i"], wrap(_set_quota),
                0, len(QUOTA_FACTORS) - 1,
            )

            kn["shed"] = self._shed_knob(spec, wrap)

            ebox = {"n": 0}

            def _escalate(n, _b=ebox, _t=t, _c=self):
                n = int(n)
                while _b["n"] < n:
                    _b["n"] += 1
                    _c.escalations_total += 1
                    if _c._daemon is not None:
                        _c._daemon.strike_tenant(
                            _t.key,
                            "controller escalation: degradation "
                            "ladder exhausted throttle and shed",
                        )

            kn["escalate"] = Knob(
                "escalate", lambda _b=ebox: _b["n"], wrap(_escalate),
                0, max(1, spec.quarantine_after),
            )

            # fleet rungs (r19): only when the daemon is wired into an
            # elastic fleet.  The setter posts a request through the
            # daemon's fleet hook (the coordinator decides and acts);
            # bound 0..1 = at most one request per tenant per daemon
            # lifetime, and like escalate the rung never relaxes.
            if (
                self._daemon is not None
                and getattr(self._daemon, "fleet_hook", None) is not None
            ):
                for action in FLEET_RUNGS:
                    fbox = {"n": 0}

                    def _fleet(n, _b=fbox, _t=t, _c=self, _a=action):
                        n = int(n)
                        while _b["n"] < n:
                            _b["n"] += 1
                            _c.fleet_requests_total += 1
                            _c._daemon.request_fleet(
                                _a, _t.key,
                                reason="controller: local degradation "
                                "ladder exhausted",
                            )

                    kn[action] = Knob(
                        action, lambda _b=fbox: _b["n"], wrap(_fleet),
                        0, 1,
                    )

        if self.ingest:
            from sntc_tpu.data.autotune import (
                AutotunePolicy,
                IngestAutotuner,
            )

            # the controller owns the ingest loop: one tuner per
            # target, ticked at most once per window when the
            # diagnosis is throughput-bound, with pipeline_depth
            # excluded (one owner per knob — the controller keeps it)
            t.tuner = IngestAutotuner(
                policy=AutotunePolicy(
                    interval_ticks=1,
                    confirm=self.policy.confirm,
                    cooldown=self.policy.cooldown,
                    max_reversals=self.policy.max_reversals,
                ),
                budget=self.budget,
                tenant=t.key,
                exclude_knobs=("pipeline_depth",),
            )

        t.knobs = kn
        for base, knob in kn.items():
            full = self._full(t, base)
            self._knobs[full] = knob
            self._defaults[full] = knob.get()
        # prime the window baseline NOW: the first scheduling round's
        # evidence (a shed burst on the opening backlog, the first
        # strikes) must land in window 1's DELTA, not vanish into a
        # cold first sample
        t.prev = self._sample(t)
        t.prev_ts = self._clock()
        t.prev_compiles = t.engine.predictor.compile_events

    # -- journal ------------------------------------------------------------

    def knob_values(self) -> Dict[str, int]:
        return {name: k.get() for name, k in sorted(self._knobs.items())}

    def knob_values_for(self, key) -> Dict[str, int]:
        """One target's live knob map, base-named (the drain-marker /
        health-dump surface)."""
        for t in self.targets:
            if t.key == key:
                return {b: k.get() for b, k in sorted(t.knobs.items())}
        return {}

    def _append_journal(self, rec: dict) -> None:
        if self.journal_path is None:
            return
        # rotating size-capped writer with the DEGRADE policy (r17):
        # one write call per record — a kill can lose the tail line,
        # never tear one (the restart reconciliation reads the tail,
        # and the writer rolls a torn partial line back out); a disk
        # failure buffers the record behind a counted storage_degraded
        # episode instead of killing the control loop
        if self._journal_writer is None:
            from sntc_tpu.resilience.storage import RotatingJsonlWriter

            self._journal_writer = RotatingJsonlWriter(
                self.journal_path, artifact="controller_journal",
            )
        self._journal_writer.write(rec)

    def _reconcile_journal(self) -> None:
        """On construction over an existing journal: log the delta
        between the journal's final knob state and this process's cold
        defaults (knobs are process-local; a crash resets them)."""
        path = self.journal_path
        if not path or not os.path.exists(path):
            return
        last, torn = None, 0
        # oldest rotated segment first (the journal rotates at a size
        # cap, r17): the knob tail may live in the CURRENT segment's
        # predecessor when a rotation landed just before the crash
        for seg in (f"{path}.2", f"{path}.1", path):
            if not os.path.exists(seg):
                continue
            with open(seg) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        torn += 1
                        continue
                    if rec.get("knobs"):
                        last = rec
        live = self.knob_values()
        journal_knobs = last.get("knobs") if last else None
        rec = {
            "action": "restart",
            "ts": self._wall(),
            "journal_knobs": journal_knobs,
            "live_knobs": live,
            "delta": (
                {
                    k: {"journal": journal_knobs.get(k), "live": v}
                    for k, v in live.items()
                    if journal_knobs.get(k) != v
                }
                if journal_knobs else None
            ),
            "torn_lines": torn,
        }
        self._append_journal(rec)
        emit_event(
            event="controller_restart",
            knobs_changed=len(rec["delta"] or {}),
            torn_lines=torn,
        )

    def _on_journal(self, rec: dict) -> None:
        """Guardrails journal callback: mirror every decision to the
        metrics plane, the event stream, and the durable journal."""
        tid, base = self._split(rec["knob"])
        labels = {} if tid is None else {"tenant": tid}
        inc(
            "sntc_ctl_decisions_total",
            action=rec["action"], knob=base, **labels,
        )
        if rec["action"] == "applied":
            set_gauge("sntc_ctl_knob_value", rec["to"], knob=base,
                      **labels)
        fields = dict(
            event="controller_decision", action=rec["action"],
            knob=base, direction=rec["direction"], value=rec["to"],
        )
        if tid is not None:
            fields["tenant"] = tid
        emit_event(**fields)
        self._append_journal(dict(
            rec, tenant=tid, ts=self._wall(), knobs=self.knob_values(),
        ))

    # -- the signal plane ---------------------------------------------------

    def _sample(self, t: _Target) -> dict:
        reg = registry()
        labels = {} if t.key is None else {"tenant": t.key}
        return {
            "batches": reg.get(
                "sntc_batches_committed_total", **labels) or 0.0,
            "rows": reg.get(
                "sntc_rows_committed_total", **labels) or 0.0,
            "shed": reg.get(
                "sntc_shed_offsets_total", **labels) or 0.0,
            "strikes": reg.get(
                "sntc_tenant_strikes_total", **labels) or 0.0,
            "hist": reg.get_histogram(
                "sntc_batch_duration_seconds", **labels),
        }

    def _window_signal(self, t: _Target, now: float) -> Optional[SloSignal]:
        """Diff this target's registry counters against the previous
        window's sample (None on the very first window — the
        controller never acts on a cold sample)."""
        cur = self._sample(t)
        compiles = t.engine.predictor.compile_events
        prev, prev_ts = t.prev, t.prev_ts
        prev_compiles = t.prev_compiles
        t.prev, t.prev_ts, t.prev_compiles = cur, now, compiles
        if prev is None or prev_ts is None:
            return None
        elapsed = max(1e-9, now - prev_ts)
        batches = int(cur["batches"] - prev["batches"])
        rows = int(cur["rows"] - prev["rows"])
        shed = int(cur["shed"] - prev["shed"])
        strikes = int(cur["strikes"] - prev["strikes"])
        p50 = p99 = None
        if cur["hist"] is not None:
            bounds = cur["hist"]["bounds"]
            prev_counts = (
                prev["hist"]["buckets"] if prev["hist"] is not None
                else [0] * len(cur["hist"]["buckets"])
            )
            deltas = [
                c - p for c, p in zip(cur["hist"]["buckets"],
                                      prev_counts)
            ]
            p50 = window_percentile(bounds, deltas, 50)
            p99 = window_percentile(bounds, deltas, 99)
            if p99 is not None and math.isinf(p99):
                # rank landed in the +Inf bucket: substitute the
                # window mean (sum/count deltas), never journal inf
                sum_d = cur["hist"]["sum"] - (
                    prev["hist"]["sum"] if prev["hist"] else 0.0
                )
                count_d = cur["hist"]["count"] - (
                    prev["hist"]["count"] if prev["hist"] else 0
                )
                p99 = (
                    sum_d / count_d if count_d > 0 else bounds[-1]
                )
            if p50 is not None and math.isinf(p50):
                p50 = p99
        try:
            backlog = t.engine.backlog_offsets()
        except Exception:
            backlog = 0
        unit = t.engine.max_batch_offsets or 1
        breakers = getattr(t.engine, "breakers", {})
        sig = SloSignal(
            batches=batches,
            rows=rows,
            rows_per_s=rows / elapsed,
            p50_ms=None if p50 is None else round(p50 * 1e3, 3),
            p99_ms=None if p99 is None else round(p99 * 1e3, 3),
            shed_offsets=shed,
            shed_rate=shed / max(1.0, shed + batches * unit),
            strikes=strikes,
            backlog=backlog,
            compile_events=compiles - (prev_compiles or 0),
            breaker_open=any(
                br.state == "open" for br in breakers.values()
            ),
            elapsed_s=elapsed,
        )
        t.last_signal = sig
        return sig

    def _violations(self, t: _Target, sig: SloSignal) -> Dict[str, float]:
        """Per-axis violation severity ratios (> 1 = violating); empty
        = compliant on every DECLARED axis.  Also refreshes the
        compliance map + gauges."""
        v: Dict[str, float] = {}
        comp: Dict[str, bool] = {}
        slo = t.slo
        if slo.slo_p99_ms is not None:
            bad = sig.p99_ms is not None and sig.p99_ms > slo.slo_p99_ms
            comp["p99"] = not bad
            if bad:
                v["p99"] = sig.p99_ms / slo.slo_p99_ms
        if slo.slo_min_rows_per_sec is not None:
            # a throughput floor binds only under demand: an idle
            # stream (no backlog) is vacuously compliant
            bad = (
                sig.backlog > 0
                and sig.rows_per_s < slo.slo_min_rows_per_sec
            )
            comp["throughput"] = not bad
            if bad:
                v["throughput"] = slo.slo_min_rows_per_sec / max(
                    sig.rows_per_s, 1e-9
                )
        if slo.slo_max_shed_rate is not None:
            bad = (
                sig.shed_offsets > 0
                and sig.shed_rate > slo.slo_max_shed_rate
            )
            comp["shed"] = not bad
            if bad:
                v["shed"] = sig.shed_rate / slo.slo_max_shed_rate
        t.compliance = comp
        labels = {} if t.key is None else {"tenant": t.key}
        for axis, ok in comp.items():
            set_gauge(
                "sntc_ctl_slo_compliant", 1.0 if ok else 0.0,
                slo=axis, **labels,
            )
        if sig.p99_ms is not None:
            set_gauge(
                "sntc_ctl_window_p99_seconds", sig.p99_ms / 1e3,
                **labels,
            )
        # sticky hold (constructor docstring): an axis violated this
        # window arms `violation_hold` further windows at its last
        # severity; an axis quiet this window burns one hold window
        held: Dict[str, float] = {}
        for axis in list(t.hold):
            left, ratio = t.hold[axis]
            if axis in v:
                continue
            if left > 0:
                held[axis] = ratio
                t.hold[axis] = (left - 1, ratio)
            else:
                del t.hold[axis]
        for axis, ratio in v.items():
            t.hold[axis] = (self.violation_hold, ratio)
        return dict(held, **v)

    # -- the controller -----------------------------------------------------

    def _platform_degraded(self) -> bool:
        """True while the shared compute plane is HOST_DEGRADED (the
        device fault domain's verdict); a failing check reads False —
        awareness must never break the control loop."""
        if self._device_check is None:
            return False
        try:
            return bool(self._device_check())
        except Exception:
            return False

    def _usable(self, t: _Target, base: str, direction: int) -> bool:
        return self.guard.usable(
            {self._full(t, base): t.knobs.get(base)}
            if t.knobs.get(base) is not None else {},
            self._full(t, base), direction,
        )

    def _tuner_has_action_space(self, t: _Target) -> bool:
        """Delegation is pointless once the tuner bound an EMPTY knob
        set (a MemorySource engine exposes no live setters) — fall
        through to the serving knobs instead.  An unbound tuner gets
        one probe window to bind."""
        if t.tuner is None:
            return False
        if t.tuner._knobs is None:
            return True
        return bool(t.tuner._knobs)

    def _all_others_compliant(self, t: _Target) -> bool:
        for other in self.targets:
            if other is t or not other.controllable():
                continue
            if other.compliance and not all(other.compliance.values()):
                return False
        return True

    def _plan(
        self, by_target: Dict[Any, Tuple[_Target, Dict[str, float]]]
    ) -> Tuple[Optional[Tuple[str, int]], Optional[_Target]]:
        """The priority ladder (module docstring): returns
        ``(serving-knob proposal or None, ingest-delegation target or
        None)``."""
        violators = [
            (t, v) for t, v in by_target.values() if v
        ]
        if violators:
            # most severe violator first; ties resolve by key order so
            # the confirm streak can accumulate deterministically
            violators.sort(
                key=lambda tv: (-max(tv[1].values()), str(tv[0].key))
            )
            t, v = violators[0]
            sig = t.last_signal
            flooding = "shed" in v or sig.strikes > 0
            if flooding and t.stream is not None:
                # degrade the violator, never its neighbors:
                # throttle → shed → ladder escalation.  While the
                # compute plane serves HOST_DEGRADED the escalate rung
                # is off the table: the collapse is device-attributed,
                # and striking a tenant for a platform fault is exactly
                # the mis-attribution the fault domain exists to stop.
                for base in ("quota", "shed", "escalate") + FLEET_RUNGS:
                    if base in FLEET_RUNGS and base not in t.knobs:
                        continue  # not wired into a fleet
                    if (
                        base == "escalate" or base in FLEET_RUNGS
                    ) and self._platform_degraded():
                        self.platform_deferrals += 1
                        continue
                    if self._usable(t, base, +1):
                        return (self._full(t, base), +1), None
                return None, None
            if "p99" in v:
                # latency is queue wait (depth) or compile churn
                # (bucket floor); as the last resort the tenant
                # admits less (its own quota) to serve within SLO
                if sig.compile_events > 0 and self._usable(
                    t, "shape_buckets", +1
                ):
                    return (self._full(t, "shape_buckets"), +1), None
                if self._usable(t, "pipeline_depth", -1):
                    return (self._full(t, "pipeline_depth"), -1), None
                if t.stream is not None and self._usable(t, "quota", +1):
                    return (self._full(t, "quota"), +1), None
                return None, None
            # throughput-bound: feed the engine first (the ingest
            # loop the controller owns), then deepen the pipeline,
            # then — only while every neighbor is compliant — take
            # more of the schedule.  A tuner that keeps producing
            # nothing (its knobs saturated or its own hysteresis
            # holding) yields to the serving knobs after `confirm`
            # idle windows, then gets the floor back once they are
            # exhausted too.
            delegate_ok = (
                sig.backlog > 0 and self._tuner_has_action_space(t)
            )
            if delegate_ok and (
                t.idle_delegations <= self.policy.confirm
            ):
                return None, t
            if self._usable(t, "pipeline_depth", +1):
                return (self._full(t, "pipeline_depth"), +1), None
            if (
                t.stream is not None
                and self._all_others_compliant(t)
                and self._usable(t, "weight", +1)
            ):
                return (self._full(t, "weight"), +1), None
            if delegate_ok:
                return None, t
            return None, None
        # no violations anywhere: relax ONE degraded knob toward its
        # cold default (escalate never relaxes — strikes were spent)
        for t in self.targets:
            if not t.controllable():
                continue
            for base in ("quota", "shed", "weight", "pipeline_depth",
                         "shape_buckets"):
                k = t.knobs.get(base)
                if k is None:
                    continue
                full = self._full(t, base)
                if full in self.guard.frozen:
                    continue
                cur, default = k.get(), self._defaults[full]
                if cur != default:
                    return (full, 1 if cur < default else -1), None
        return None, None

    def step(
        self, signals: Dict[Any, SloSignal]
    ) -> Optional[dict]:
        """One closed observation window over per-target signals
        (:meth:`on_tick` computes them from the registry; tests pass
        synthetic maps).  At most ONE knob moves: a serving knob
        through the shared guardrails, or — when no serving proposal
        is live — one delegated ingest-tuner step."""
        if not signals:
            return None
        inc("sntc_ctl_windows_total")
        by_key = {t.key: t for t in self.targets}
        by_target: Dict[Any, Tuple[_Target, Dict[str, float]]] = {}
        for key, sig in signals.items():
            t = by_key.get(key)
            if t is None:
                continue
            t.last_signal = sig
            if not t.controllable():
                continue
            by_target[key] = (t, self._violations(t, sig))
        prop, delegate = self._plan(by_target)

        def _fields():
            if prop is None:
                return {}
            tid, _base = self._split(prop[0])
            t = by_key.get(tid)
            return (
                t.last_signal.as_fields()
                if t is not None and t.last_signal is not None else {}
            )

        rec = self.guard.observe(
            lambda: prop, self._knobs, _fields,
            on_applied=None,
        )
        if rec is None and prop is None and delegate is not None:
            irec = (
                delegate.tuner.on_tick(delegate.engine)
                if delegate.tuner is not None else None
            )
            if irec is None:
                delegate.idle_delegations += 1
            else:
                delegate.idle_delegations = 0
            if irec is not None:
                self.delegated_total += 1
                labels = (
                    {} if delegate.key is None
                    else {"tenant": delegate.key}
                )
                inc(
                    "sntc_ctl_decisions_total", action="delegated",
                    knob=irec["knob"], **labels,
                )
                drec = {
                    "action": "delegated",
                    "tenant": delegate.key,
                    "knob": irec["knob"],
                    "window": self.guard.windows,
                    "ingest": irec,
                    "ts": self._wall(),
                    "knobs": self.knob_values(),
                }
                emit_event(
                    event="controller_decision", action="delegated",
                    knob=irec["knob"],
                    **({} if delegate.key is None
                       else {"tenant": delegate.key}),
                )
                self._append_journal(drec)
                return drec
        return rec

    def on_tick(self) -> Optional[dict]:
        """Owner cadence: cheap counter bump until the observation
        window closes, then sample + step.  Exceptions propagate —
        the OWNER (daemon tick / supervisor tick) wraps this in the
        degrade-never-kill contract."""
        self._ticks += 1
        if self._ticks % self.interval_ticks:
            return None
        now = self._clock()
        signals: Dict[Any, SloSignal] = {}
        for t in self.targets:
            sig = self._window_signal(t, now)
            if sig is not None:
                signals[t.key] = sig
        return self.step(signals)

    # -- evidence -----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        out = {
            "windows": self.guard.windows,
            "decisions": self.guard.decisions_total,
            "applied": len(self.guard.applied()),
            "delegated": self.delegated_total,
            "escalations": self.escalations_total,
            "fleet_requests": self.fleet_requests_total,
            "platform_deferrals": self.platform_deferrals,
            "platform_degraded": self._platform_degraded(),
            "frozen": sorted(self.guard.frozen),
            "knobs": self.knob_values(),
            "recent": self.guard.decisions[-8:],
            "journal": self.journal_path,
        }
        if self.budget is not None:
            out["budget"] = self.budget.snapshot()
        if self.ingest:
            out["ingest"] = {
                (t.key or "_"): t.tuner.stats()
                for t in self.targets if t.tuner is not None
            }
        return out

    def slo_status(self) -> Dict[str, Any]:
        """The ``status()["slo"]`` block: per-target declared SLOs,
        per-axis compliance, and the last window's signal."""
        out: Dict[str, Any] = {}
        for t in self.targets:
            sig = t.last_signal
            out[t.key or "_"] = {
                "declared": t.slo.as_dict(),
                "compliant": (
                    all(t.compliance.values())
                    if t.compliance else None
                ),
                "axes": dict(t.compliance),
                "window": sig.as_fields() if sig is not None else None,
            }
        return out
