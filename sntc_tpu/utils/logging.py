"""Structured JSONL metrics logging — the MetricsSystem/event-log analog.

Behavioral spec: SURVEY.md §5.5: Spark exposes Codahale metrics sinks and
JSON event logs; MLlib models keep ``objectiveHistory``.  Here: an
append-only JSONL event stream (one object per line: monotonic step,
wall-clock, arbitrary scalar fields) that tooling can tail — plus the
models' ``summary.objectiveHistory`` (API parity, implemented on each
estimator).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional


class MetricsLogger:
    """Append-only JSONL logger: ``logger.log(event="fit", loss=0.3)``."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._step = 0
        self._t0 = time.perf_counter()
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            # truncate: one run per file
            open(path, "w").close()

    def log(self, **fields: Any) -> Dict[str, Any]:
        record = {
            "step": self._step,
            "elapsed_s": round(time.perf_counter() - self._t0, 6),
            **fields,
        }
        self._step += 1
        if self.path:
            with open(self.path, "a") as f:  # storage: unbounded(caller-owned log path)
                f.write(json.dumps(record) + "\n")
        return record

    def read_all(self):
        if not self.path or not os.path.exists(self.path):
            return []
        with open(self.path) as f:
            return [json.loads(line) for line in f if line.strip()]
