"""Persistent XLA compilation cache (SURVEY.md §3.5 cold-start).

Spark pays no per-process compile; JAX pays full XLA compilation on the
first fit of every process (~8-13× the warm fit on the bench configs).
JAX's persistent compilation cache closes most of that gap: compiled
executables are written to a directory keyed by (HLO, flags, platform),
so the SECOND process's "cold" fit only pays trace + cache lookup.

Opt-out with ``SNTC_NO_COMPILE_CACHE=1``; the directory defaults to
``~/.cache/sntc_tpu_xla`` and can be moved with
``JAX_COMPILATION_CACHE_DIR`` (the stock JAX env var wins if set, since
``jax.config`` reads it at import).
"""

from __future__ import annotations

import os


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Turn on JAX's on-disk compilation cache; returns the dir (or None
    when disabled).  Safe to call more than once and before/after other
    jax.config updates; must run before the first compilation to help."""
    if os.environ.get("SNTC_NO_COMPILE_CACHE"):
        return None
    import jax

    cache_dir = (
        cache_dir
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or os.path.join(
            os.path.expanduser("~"), ".cache", "sntc_tpu_xla"
        )
    )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # default min compile time is 1s, which skips most of the small
    # per-stage programs (binning, scaler aggregates) whose compiles
    # still add up across a pipeline; cache everything non-trivial
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return cache_dir
