"""Persistent XLA compilation cache (SURVEY.md §3.5 cold-start).

Spark pays no per-process compile; JAX pays full XLA compilation on the
first fit of every process (~8-13× the warm fit on the bench configs).
JAX's persistent compilation cache closes most of that gap: compiled
executables are written to a directory keyed by (HLO, flags, platform),
so the SECOND process's "cold" fit only pays trace + cache lookup.

That key does NOT include the host CPU feature set, and XLA:CPU
executables are AOT-compiled for the build host's features — serving an
entry compiled on a differently-featured host is a latent SIGILL (the
exact "Compile machine features ... vs host machine features" warning
observed after a mid-round host change, VERDICT r4 weak #4).  The cache
is therefore partitioned into per-host subdirectories keyed by a digest
of ``/proc/cpuinfo`` flags: a foreign-host artifact is a clean miss, not
a potential crash.  (TPU executables don't depend on host features, so
the partition only costs a one-time recompile after a host change.)

Opt-out with ``SNTC_NO_COMPILE_CACHE=1``; the base directory defaults to
``~/.cache/sntc_tpu_xla`` and can be moved with
``JAX_COMPILATION_CACHE_DIR``.  The per-host partition is applied BENEATH
whichever base is chosen — including a user-set env dir, since a shared
pre-warmed cache from a differently-featured host is exactly the SIGILL
hazard the partition exists for; ``SNTC_CACHE_NO_HOST_KEY=1`` restores
the single shared dir (pre-r5 behavior).
"""

from __future__ import annotations

import hashlib
import os
import platform as _platform


def host_feature_signature() -> str:
    """Stable 12-hex digest of this host's CPU feature flags.

    Reads the first ``flags``/``Features`` line of ``/proc/cpuinfo``
    (x86/arm spellings) and hashes the sorted flag set, so reordering or
    duplicate processor blocks don't change the signature but any
    added/removed ISA feature does.  Falls back to the machine arch when
    cpuinfo is unreadable (non-Linux), which still separates
    cross-architecture caches.
    """
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                key = line.split(":", 1)[0].strip().lower()
                if key in ("flags", "features"):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    return hashlib.sha1(flags.encode()).hexdigest()[:12]
    except OSError:
        pass
    return (_platform.machine() or "unknown-arch")[:12]


def resolve_cache_dir(cache_dir: str | None = None) -> str | None:
    """The directory the cache will use, without touching jax.config.

    None when the cache is disabled.  Separated from
    :func:`enable_persistent_cache` so tests can check the host-key
    partition without initializing a backend.
    """
    if os.environ.get("SNTC_NO_COMPILE_CACHE"):
        return None
    base = (
        cache_dir
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or os.path.join(os.path.expanduser("~"), ".cache", "sntc_tpu_xla")
    )
    if os.environ.get("SNTC_CACHE_NO_HOST_KEY"):
        return base
    part = f"host-{host_feature_signature()}"
    if os.path.basename(os.path.normpath(base)) == part:
        # base is ALREADY the per-host partition — e.g. the env var was
        # rewritten by a prior enable_persistent_cache(); nesting a
        # second host-<sig> level would orphan every cached entry
        return base
    return os.path.join(base, part)


def fsck_compile_cache(
    cache_dir: str | None = None, *, repair: bool = True
) -> dict:
    """Doctor the persistent XLA compilation cache (r18, the ``sntc
    fsck`` extension): a crash or ENOSPC mid-write can leave
    zero-length, unreadable, or orphaned-tmp entries under the
    directory :func:`enable_persistent_cache` manages — jax then either
    warns per hit or, in the worst case, dies deserializing a torn
    executable.  Poisoned entries are QUARANTINED to ``.corrupt/``
    beside the cache (the r17 ``.corrupt/`` discipline — evidence
    preserved, never deleted) so the next compile is a clean miss that
    RECOMPILES instead of crashing; ``*.tmp`` orphans are swept.

    Cache entries are opaque compressed executables, so "verify" means
    structural health: readable, non-empty, not a tmp orphan — content
    validity stays jax's job (a quarantined entry costs one recompile,
    which is exactly the safe outcome).

    Returns a machine-readable report mirroring the storage-plane fsck
    shape; ``repair=False`` reports without moving anything."""
    resolved = cache_dir or resolve_cache_dir()
    report: dict = {
        "cache_dir": resolved,
        "repair": bool(repair),
        "checked": 0,
        "quarantined": [],
        "cleaned": [],
        "errors": [],
        "ok": True,
    }
    if resolved is None or not os.path.isdir(resolved):
        return report

    def _quarantine(path: str, detail: str) -> None:
        entry = {"path": path, "detail": detail}
        if not repair:
            report["errors"].append(entry)
            return
        # the storage plane's shared quarantine: .corrupt/ beside the
        # blob + a journaled repair record (storage_repair.jsonl under
        # the cache dir) — 'quarantine' means one thing repo-wide
        from sntc_tpu.resilience.storage import quarantine_blob

        dest = quarantine_blob(
            path, artifact="compile_cache", detail=detail,
            root=resolved,
        )
        if dest is None:
            report["errors"].append(
                dict(entry, detail=f"{detail}; quarantine failed")
            )
            return
        entry["quarantined_to"] = dest
        report["quarantined"].append(entry)

    for dirpath, dirs, files in os.walk(resolved):
        dirs[:] = [d for d in dirs if d != ".corrupt"]
        for name in files:
            if name.startswith("storage_repair.jsonl"):
                continue  # the quarantine journal, not a cache entry
            path = os.path.join(dirpath, name)
            stem, _, suffix = name.rpartition(".tmp")
            if stem and (not suffix or suffix.lstrip("-").isdigit()):
                # an orphaned atomic-write temp: a cache writer died
                # mid-publish; the entry it was building never existed
                report["checked"] += 1
                if repair:
                    try:
                        os.unlink(path)
                        report["cleaned"].append({"path": path})
                    except OSError as e:
                        report["errors"].append(
                            {"path": path,
                             "detail": f"unlink failed: {e}"}
                        )
                else:
                    report["errors"].append(
                        {"path": path, "detail": "orphaned tmp file"}
                    )
                continue
            report["checked"] += 1
            try:
                size = os.path.getsize(path)
                if size == 0:
                    _quarantine(path, "zero-length cache entry")
                    continue
                # readable? (permission damage / torn inode both
                # surface here) — one short read, not a full load
                with open(path, "rb") as f:
                    f.read(64)
            except OSError as e:
                _quarantine(path, f"unreadable cache entry: {e}")
    report["ok"] = not report["errors"]
    return report


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Turn on JAX's on-disk compilation cache; returns the dir (or None
    when disabled).  Safe to call more than once and before/after other
    jax.config updates; must run before the first compilation to help."""
    resolved = resolve_cache_dir(cache_dir)
    if resolved is None:
        return None
    import jax

    os.makedirs(resolved, exist_ok=True)
    # ADVICE r5: when JAX_COMPILATION_CACHE_DIR is set, jax enables the
    # cache at the UNpartitioned base at import time — rewrite the env
    # var to the per-host path so compiles that consult the env (pre- or
    # post-enable, this process or subprocesses inheriting the env)
    # can never read/write foreign-host entries from the shared base,
    # the exact SIGILL hazard the partition exists to prevent
    os.environ["JAX_COMPILATION_CACHE_DIR"] = resolved
    jax.config.update("jax_compilation_cache_dir", resolved)
    # default min compile time is 1s, which skips most of the small
    # per-stage programs (binning, scaler aggregates) whose compiles
    # still add up across a pipeline; cache everything non-trivial
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return resolved
