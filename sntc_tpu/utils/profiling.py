"""Profiling hooks — the SparkListener/Web-UI timeline analog.

Behavioral spec: SURVEY.md §5.1: Spark's per-stage timelines come from the
listener bus; the TPU-native equivalents are (a) ``jax.profiler`` traces
viewable in TensorBoard/Perfetto (XLA op-level — far deeper than Spark's
stage view) and (b) a lightweight wall-clock step timer for the
host-visible phases (ingest, fit, transform).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """``with profile_trace("/tmp/trace"):`` — captures an XLA profiler
    trace for TensorBoard/Perfetto."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class TransferLedger:
    """Host↔device transfer accounting for the fused serving path.

    The whole-pipeline fusion compiler (``sntc_tpu.fuse``) exists to
    collapse per-stage host round trips into one program; this ledger is
    the EVIDENCE — every fused-segment dispatch records how many host
    arrays it uploaded and how many device outputs its finalize
    materialized.  Counts are per-DISPATCH (one fused program call):
    the per-MICRO-BATCH evidence the bench journals divides the upload/
    download deltas by the ENGINE's committed batch count, so a pipeline
    broken into N segments honestly reports N uploads per batch instead
    of hiding behind a per-dispatch ratio that is ~1 by construction.
    Thread-safe: the pipelined engine dispatches on the engine thread
    and finalizes on the delivery thread.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.dispatches = 0
        self.uploads = 0
        self.downloads = 0
        self.upload_bytes = 0
        self.download_bytes = 0

    def record_uploads(self, count: int, nbytes: int = 0) -> None:
        with self._lock:
            self.dispatches += 1
            self.uploads += int(count)
            self.upload_bytes += int(nbytes)

    def record_downloads(self, count: int, nbytes: int = 0) -> None:
        with self._lock:
            self.downloads += int(count)
            self.download_bytes += int(nbytes)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "dispatches": self.dispatches,
                "uploads": self.uploads,
                "downloads": self.downloads,
                "upload_bytes": self.upload_bytes,
                "download_bytes": self.download_bytes,
            }

    def reset(self) -> None:
        with self._lock:
            self.dispatches = self.uploads = self.downloads = 0
            self.upload_bytes = self.download_bytes = 0


# process-global instance the fused segments write to; bench/tests diff
# snapshots around a measured window (see sntc_tpu.fuse.planner)
_TRANSFER_LEDGER = TransferLedger()


def transfer_ledger() -> TransferLedger:
    return _TRANSFER_LEDGER


class StepTimer:
    """Named wall-clock phases: ``with timer.phase("fit"): ...``."""

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> Dict[str, float]:
        return dict(sorted(self.totals.items(), key=lambda kv: -kv[1]))
