"""Profiling hooks — the SparkListener/Web-UI timeline analog.

Behavioral spec: SURVEY.md §5.1: Spark's per-stage timelines come from the
listener bus; the TPU-native equivalents are (a) ``jax.profiler`` traces
viewable in TensorBoard/Perfetto (XLA op-level — far deeper than Spark's
stage view; see also ``sntc_tpu.obs.trace.device_trace``), (b) the host
span tracer (``sntc_tpu.obs.span``) for the engine's stage timeline, and
(c) the transfer ledger below, whose counters also mirror into the
``sntc_tpu.obs`` metrics registry (``sntc_transfer_*`` series).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """``with profile_trace("/tmp/trace"):`` — captures an XLA profiler
    trace for TensorBoard/Perfetto."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class TransferLedger:
    """Host↔device transfer accounting for the fused serving path.

    The whole-pipeline fusion compiler (``sntc_tpu.fuse``) exists to
    collapse per-stage host round trips into one program; this ledger is
    the EVIDENCE — every fused-segment dispatch records how many host
    arrays it uploaded and how many device outputs its finalize
    materialized.  Counts are per-DISPATCH (one fused program call):
    the per-MICRO-BATCH evidence the bench journals divides the upload/
    download deltas by the ENGINE's committed batch count, so a pipeline
    broken into N segments honestly reports N uploads per batch instead
    of hiding behind a per-dispatch ratio that is ~1 by construction.
    Thread-safe: the pipelined engine dispatches on the engine thread
    and finalizes on the delivery thread.

    **Attachment (r13):** the process-global instance
    (:func:`transfer_ledger`) used to be the ONLY ledger, which
    conflated every engine's counts — two tenant streams on one device
    were indistinguishable.  Engines now construct their OWN ledger and
    scope it around dispatch (:func:`ledger_scope`); the fused segment
    captures :func:`active_ledgers` at dispatch time and records into
    all of them, so the closure attributes correctly even though its
    finalize may run on the delivery thread.  The global stays the
    default process-wide view.

    ``tenant`` names the engine's tenant: the ledger then also mirrors
    into the ``sntc_transfer_*{tenant=...}`` metrics series.  The
    global ledger mirrors into the unlabeled series; anonymous
    per-engine ledgers (``tenant=None``) keep their own counts but do
    not mirror — the unlabeled series stays exactly the global view.
    """

    def __init__(self, tenant: Optional[str] = None, *,
                 _mirror_unlabeled: bool = False):
        self._lock = threading.Lock()
        self.tenant = tenant
        if tenant is not None:
            self._mirror_labels: Optional[Dict[str, str]] = {
                "tenant": tenant
            }
        elif _mirror_unlabeled:
            self._mirror_labels = {}
        else:
            self._mirror_labels = None
        self.dispatches = 0
        self.uploads = 0
        self.downloads = 0
        self.upload_bytes = 0
        self.download_bytes = 0

    def _mirror(self, uploads=0, upload_bytes=0, downloads=0,
                download_bytes=0, dispatches=0) -> None:
        labels = self._mirror_labels
        if labels is None:
            return
        from sntc_tpu.obs.metrics import inc

        if dispatches:
            inc("sntc_transfer_dispatches_total", dispatches, **labels)
        if uploads:
            inc("sntc_transfer_uploads_total", uploads, **labels)
        if upload_bytes:
            inc("sntc_transfer_upload_bytes_total", upload_bytes,
                **labels)
        if downloads:
            inc("sntc_transfer_downloads_total", downloads, **labels)
        if download_bytes:
            inc("sntc_transfer_download_bytes_total", download_bytes,
                **labels)

    def record_uploads(self, count: int, nbytes: int = 0) -> None:
        with self._lock:
            self.dispatches += 1
            self.uploads += int(count)
            self.upload_bytes += int(nbytes)
        self._mirror(uploads=int(count), upload_bytes=int(nbytes),
                     dispatches=1)

    def record_downloads(self, count: int, nbytes: int = 0) -> None:
        with self._lock:
            self.downloads += int(count)
            self.download_bytes += int(nbytes)
        self._mirror(downloads=int(count), download_bytes=int(nbytes))

    def record_movement(self, uploads: int = 0, upload_bytes: int = 0,
                        downloads: int = 0, download_bytes: int = 0) -> None:
        """Substrate-level host↔device movement OUTSIDE a fused dispatch
        (collective shard placement, mesh-resize re-placement, OOM
        row-split re-uploads — r22): arrays and bytes are counted but NOT
        a dispatch, so the ``dispatches`` series keeps meaning "fused
        program calls" and per-dispatch ratios stay honest."""
        with self._lock:
            self.uploads += int(uploads)
            self.upload_bytes += int(upload_bytes)
            self.downloads += int(downloads)
            self.download_bytes += int(download_bytes)
        self._mirror(uploads=int(uploads), upload_bytes=int(upload_bytes),
                     downloads=int(downloads),
                     download_bytes=int(download_bytes))

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "dispatches": self.dispatches,
                "uploads": self.uploads,
                "downloads": self.downloads,
                "upload_bytes": self.upload_bytes,
                "download_bytes": self.download_bytes,
            }

    def reset(self) -> None:
        with self._lock:
            self.dispatches = self.uploads = self.downloads = 0
            self.upload_bytes = self.download_bytes = 0


# process-global instance: the default process-wide view every fused
# dispatch records into; bench/tests diff snapshots around a measured
# window (see sntc_tpu.fuse.planner).  Scoped per-engine ledgers record
# ALONGSIDE it, never instead of it.
_TRANSFER_LEDGER = TransferLedger(_mirror_unlabeled=True)

# per-thread stack of additionally-scoped ledgers.  Thread-local (not a
# contextvar) on purpose: the scope is pushed on the ENGINE thread
# around dispatch, and the fused segment snapshots active_ledgers()
# into its finalize closure — cross-thread finalize needs no
# propagation because attribution is captured at dispatch time.
_scoped = threading.local()


def transfer_ledger() -> TransferLedger:
    return _TRANSFER_LEDGER


@contextlib.contextmanager
def ledger_scope(ledger: TransferLedger):
    """Attribute fused-segment transfers dispatched inside the block to
    ``ledger`` (in addition to the process-global view)."""
    stack = getattr(_scoped, "stack", None)
    if stack is None:
        stack = _scoped.stack = []
    stack.append(ledger)
    try:
        yield ledger
    finally:
        stack.pop()


def active_ledgers() -> tuple:
    """The ledgers a dispatch happening NOW should record into: the
    process-global one plus any :func:`ledger_scope` stack on this
    thread.  Callers snapshot this at dispatch time and carry it into
    their finalize closures (see ``fuse.planner``)."""
    stack = getattr(_scoped, "stack", None)
    if not stack:
        return (_TRANSFER_LEDGER,)
    return (_TRANSFER_LEDGER, *stack)
