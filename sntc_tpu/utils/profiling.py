"""Profiling hooks — the SparkListener/Web-UI timeline analog.

Behavioral spec: SURVEY.md §5.1: Spark's per-stage timelines come from the
listener bus; the TPU-native equivalents are (a) ``jax.profiler`` traces
viewable in TensorBoard/Perfetto (XLA op-level — far deeper than Spark's
stage view) and (b) a lightweight wall-clock step timer for the
host-visible phases (ingest, fit, transform).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """``with profile_trace("/tmp/trace"):`` — captures an XLA profiler
    trace for TensorBoard/Perfetto."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Named wall-clock phases: ``with timer.phase("fit"): ...``."""

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> Dict[str, float]:
        return dict(sorted(self.totals.items(), key=lambda kv: -kv[1]))
