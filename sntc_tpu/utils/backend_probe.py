"""Default-backend liveness probe (SURVEY.md §0 environment reality).

On this class of host the default JAX platform is a remote TPU tunnel
that can HANG forever inside backend init (``jax.devices()``) when the
tunnel is down — there is no interruptible handle, so the only safe
test is a subprocess we can kill.  Both ``bench.py`` and the
``python -m sntc_tpu`` CLI use this to fall back to CPU (clearly
labeled) instead of hanging a user's terminal.

Resilience: the probe is policy-driven, not single-shot — one flaky
tunnel handshake no longer forces CPU fallback (VERDICT r5: every probe
in ``tpu_probe_log.jsonl`` timed out exactly once at rc=124 with no
second chance).  ``SNTC_PROBE_ATTEMPTS`` (default 2) sets the attempt
budget; ``SNTC_PROBE_TIMEOUT_S`` remains the TOTAL stall bound, split
evenly across attempts and enforced as the policy deadline.  Backoff
between attempts is the deterministic ``RetryPolicy`` schedule, and
each attempt emits structured events at site ``probe.init`` (which is
also a fault-injection point).
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys
import time

from sntc_tpu.resilience import (
    RetryExhausted,
    RetryPolicy,
    fault_point,
    with_retries,
)
from sntc_tpu.resilience.policy import int_from_env

_OK_TTL_S = 300.0


class _ProbeFailed(RuntimeError):
    """One probe attempt failed (nonzero rc or timeout) — retryable."""


def _probe_policy(deadline_s: float | None = None) -> RetryPolicy:
    """The probe's retry budget.  ``SNTC_PROBE_TIMEOUT_S`` stays the
    TOTAL bound (this module exists to not hang terminals): it becomes
    the policy deadline and is split evenly across
    ``SNTC_PROBE_ATTEMPTS`` per-attempt subprocess timeouts, so adding
    attempts never multiplies the worst-case stall."""
    attempts = int_from_env("SNTC_PROBE_ATTEMPTS", 2, minimum=1)
    # backoff between attempts stays short (a tunnel that answers at
    # all tends to answer quickly once warm)
    return RetryPolicy(
        max_attempts=attempts, base_delay_s=1.0, multiplier=2.0,
        max_delay_s=15.0, jitter=0.1, seed=0, deadline_s=deadline_s,
    )


def _ok_marker() -> str:
    """Marker path, keyed on the backend-relevant environment.

    The probe subprocess inherits this process's env, so a success under
    ``JAX_PLATFORMS=cpu`` proves nothing about the tunnel-default
    backend; caching it un-keyed would suppress the probe for
    tunnel-default processes for 5 minutes (ADVICE r4).  Hashing
    ``JAX_PLATFORMS`` into the filename keeps the two verdicts apart.
    """
    plats = os.environ.get("JAX_PLATFORMS", "")
    suffix = hashlib.sha1(plats.encode()).hexdigest()[:12]
    return os.path.join(
        os.path.expanduser("~"), ".cache", f"sntc_tpu_probe_ok_{suffix}"
    )


def probe_default_backend(
    timeout_s: float | None = None, *, specific_env: str | None = None,
    use_cache: bool = True,
) -> bool:
    """True if the default JAX backend initializes within the timeout.

    Timeout resolution, specific-overrides-generic: ``specific_env``
    (e.g. ``BENCH_PROBE_TIMEOUT_S``) when set, else
    ``SNTC_PROBE_TIMEOUT_S``, else 180; ``0`` disables the probe and
    trusts the backend.  A success is cached in a marker file for
    5 minutes so repeated CLI calls on a healthy backend don't pay a
    full subprocess backend init each time (failures are never cached —
    a tunnel can come back any moment).  ``use_cache=False`` forces a
    REAL probe: a caller asking whether a just-dead device came back
    must not be answered from stale success evidence."""
    if timeout_s is None:
        raw = None
        if specific_env:
            raw = os.environ.get(specific_env)
        if raw is None:
            raw = os.environ.get("SNTC_PROBE_TIMEOUT_S", 180)
        try:
            timeout_s = float(raw)
        except (TypeError, ValueError):
            print(
                f"sntc_tpu: malformed probe timeout {raw!r}; using 180 s",
                file=sys.stderr,
            )
            timeout_s = 180.0
    if timeout_s <= 0:
        return True
    marker = _ok_marker()
    if use_cache:
        try:
            if time.time() - os.path.getmtime(marker) < _OK_TTL_S:
                return True
        except OSError:
            pass
    policy = _probe_policy(deadline_s=timeout_s)
    attempt_timeout = timeout_s / policy.max_attempts

    def _attempt() -> None:
        fault_point("probe.init")
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=attempt_timeout,
                capture_output=True,
            )
        except subprocess.TimeoutExpired:
            raise _ProbeFailed(
                f"backend init timed out after {attempt_timeout:g}s"
            ) from None
        if proc.returncode != 0:
            raise _ProbeFailed(f"backend init exited rc={proc.returncode}")

    try:
        with_retries(_attempt, policy, site="probe.init")
        ok = True
    except (RetryExhausted, _ProbeFailed):
        ok = False
    if ok:
        try:
            os.makedirs(os.path.dirname(marker), exist_ok=True)
            with open(marker, "w"):
                pass
        except OSError:
            pass
    return ok


def probe_for_recovery(timeout_s: float | None = None) -> bool:
    """The compute-plane fault domain's recovery probe (r18): same
    subprocess liveness check, but bounded by
    ``SNTC_RECOVERY_PROBE_TIMEOUT_S`` (default 20 s) instead of the
    startup budget — a HOST_DEGRADED predictor probes periodically from
    a background thread, and each probe must stay short enough that a
    still-dead tunnel never stacks minutes of subprocess waits.

    The 5-minute success-marker cache is BYPASSED: the whole question
    is whether a device that just died came back, and a marker written
    minutes before the death would answer yes from stale evidence —
    flapping the domain OK → dead dispatch → degraded on every probe
    interval.  A genuine success still refreshes the marker for the
    startup-probe callers."""
    if timeout_s is None:
        raw = os.environ.get("SNTC_RECOVERY_PROBE_TIMEOUT_S", 20)
        try:
            timeout_s = float(raw)
        except (TypeError, ValueError):
            timeout_s = 20.0
    return probe_default_backend(timeout_s, use_cache=False)


def add_platform_arg(parser) -> None:
    """The shared ``--platform`` CLI argument."""
    parser.add_argument(
        "--platform", default=None,
        help="force a JAX platform (e.g. 'cpu'); default probes the "
        "backend and falls back to cpu if the TPU tunnel is unreachable",
    )


def resolve_platform(
    requested: str | None, *, specific_env: str | None = None
) -> str | None:
    """The platform to force, or None to trust the default backend.

    ``requested`` wins when given.  The probe is skipped only when this
    process has ALREADY pinned a cpu-only platform (tests, embedding
    callers) or already initialized a backend — NOT merely because
    ``jax_platforms`` is set: the host sitecustomize pre-imports jax
    with ``JAX_PLATFORMS=axon`` in every process, so a bare truthiness
    test would disable the probe on exactly the hung-tunnel host class
    it exists for."""
    if requested:
        return requested
    if "jax" in sys.modules:
        import jax

        plats = jax.config.jax_platforms
        if plats and all(
            p.strip() == "cpu" for p in plats.split(",") if p.strip()
        ):
            return None  # cpu-only cannot hang; probing would be a stall
        try:
            from jax._src import xla_bridge

            if getattr(xla_bridge, "_backends", None):
                return None  # a live backend already initialized here
        except Exception:
            pass
    if not probe_default_backend(specific_env=specific_env):
        print(
            "sntc_tpu: default JAX backend unreachable (probe timeout); "
            "falling back to platform=cpu",
            file=sys.stderr,
        )
        return "cpu"
    return None


# -- probed peaks (r21): the roofline denominators -------------------------

#: platform -> (peak FLOP/s, peak HBM/DRAM bytes/s, source).  The
#: accelerator rows are datasheet numbers for the serving chip class
#: (v5e-like: 197 TFLOP/s bf16, 819 GB/s HBM); the CPU row is an
#: order-of-magnitude ESTIMATE so CPU MFU figures are honest about
#: their provenance (``peak_source`` travels with every number).
_PEAK_TABLE = {
    "tpu": (1.97e14, 8.19e11, "datasheet"),
    "axon": (1.97e14, 8.19e11, "datasheet"),
    "cpu": (2.0e11, 5.0e10, "estimate"),
}


def probed_peaks(platform: Optional[str] = None) -> dict:
    """Peak FLOP/s and memory bandwidth for ``platform`` (default: the
    current JAX default backend), for MFU/roofline accounting
    (``sntc_tpu.obs.cost``).

    ``SNTC_PEAK_FLOPS`` / ``SNTC_PEAK_BW`` override the static table
    (measured numbers from a real chip beat any datasheet); overrides
    flip ``peak_source`` to ``"env"``.  Unknown platforms fall back to
    the CPU estimate row."""
    if platform is None:
        import jax

        platform = jax.default_backend()
    flops, bw, source = _PEAK_TABLE.get(platform, _PEAK_TABLE["cpu"])
    env_f = os.environ.get("SNTC_PEAK_FLOPS")
    env_b = os.environ.get("SNTC_PEAK_BW")
    if env_f:
        flops = float(env_f)
        source = "env"
    if env_b:
        bw = float(env_b)
        source = "env"
    return {
        "platform": platform,
        "flops": flops,
        "bw": bw,
        "peak_source": source,
    }
