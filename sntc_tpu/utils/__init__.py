from sntc_tpu.utils.compile_cache import enable_persistent_cache
from sntc_tpu.utils.logging import MetricsLogger
from sntc_tpu.utils.profiling import profile_trace, StepTimer

__all__ = ["MetricsLogger", "profile_trace", "StepTimer"]
