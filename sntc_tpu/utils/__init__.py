from sntc_tpu.utils.compile_cache import enable_persistent_cache
from sntc_tpu.utils.logging import MetricsLogger
from sntc_tpu.utils.profiling import (
    TransferLedger,
    ledger_scope,
    profile_trace,
    transfer_ledger,
)

__all__ = [
    "MetricsLogger",
    "profile_trace",
    "TransferLedger",
    "transfer_ledger",
    "ledger_scope",
    "enable_persistent_cache",
]
