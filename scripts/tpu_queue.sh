#!/bin/bash
# TPU run queue: fires the round's remaining on-chip benchmark runs the
# moment the tunnel answers a COMPUTE probe (device listing alone can
# succeed while execution hangs), one bench invocation per run so a
# tunnel death mid-queue costs one run, not the suite.  Each completed
# run journals itself to bench_runs.jsonl (bench.py:_journal_run); this
# script only sequences and logs attempts.
#
# Replaces tpu_probe_loop.sh while active — the tunnel serializes
# clients, so a concurrent probe would time out against a busy tunnel
# (observed 2026-07-31 03:54Z: probe rc=124 while a bench run held the
# tunnel).  Probe results are appended to the same tpu_probe_log.jsonl.
#
# Queue order: flagship first (the headline must land in any window),
# then the cheap configs, then trees (longest compiles), then --mfu and
# the full-scale rows.  An attempt only advances the queue if its output
# shows platform=tpu (bench.py falls back to CPU on a dead tunnel — that
# journals harmlessly but does not satisfy the queue).  After
# MAX_ATTEMPTS failed tries an item is skipped so one pathological run
# cannot starve the rest.
set -u
cd /root/repo
PROBE_LOG=tpu_probe_log.jsonl
QLOG=tpu_queue_log.jsonl
POS_FILE=.tpu_queue_pos
MAX_ATTEMPTS=2

QUEUE=(
  "timeout 1500 python bench.py --config 2"
  "timeout 900 python bench.py --config 1"
  "timeout 900 python bench.py --config 1"
  "timeout 1500 python bench.py --config 5"
  "timeout 1800 python bench.py --config 4"
  "timeout 2700 python bench.py --config 3"
  "timeout 1800 python bench.py --mfu"
  "timeout 900 python scripts/profile_config1.py | tee profile_config1_tpu.jsonl"
  "BENCH_ROWS=2800000 timeout 3600 python bench.py --config 2"
  "BENCH_ROWS=2800000 timeout 3600 python bench.py --config 4"
  "BENCH_ROWS=2800000 timeout 5400 python bench.py --config 3"
  "timeout 1800 python bench.py --families"
)
# config 1 runs twice ON PURPOSE: two separate processes — the second's
# journaled cold_value ≈ warm proves the persistent compile cache works
# through the tunnel (VERDICT r3 item 7).  profile_config1 captures the
# on-chip stage-by-stage floor analysis (item 5); tee keeps the output
# while still exposing platform:"tpu" to the advance check.

pos=$(cat "$POS_FILE" 2>/dev/null || echo 0)
attempts=0

# stop firing new runs before the driver's own end-of-round bench: the
# tunnel serializes clients, so a queue run still holding it at round
# end would starve the driver's BENCH_r03 capture.  Override/disable
# with SNTC_QUEUE_DEADLINE_UTC (empty = no deadline).
DEADLINE="${SNTC_QUEUE_DEADLINE_UTC:-2026-07-31T15:05:00Z}"
past_deadline() {
  [ -n "$DEADLINE" ] || return 1
  [ "$(date -u +%s)" -ge "$(date -u -d "$DEADLINE" +%s)" ]
}

probe() {
  TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  RAW=$(timeout 180 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256))
print('PROBE_OK', jax.devices()[0].platform, float((x @ x).sum()))
" 2>&1)
  PRC=$?
  OUT=$(echo "$RAW" | grep PROBE_OK | tail -1)
  if echo "$OUT" | grep -q "PROBE_OK tpu\|PROBE_OK axon"; then
    echo "{\"ts\": \"$TS\", \"ok\": true, \"probe\": \"$OUT (queue)\"}" >> $PROBE_LOG
    touch .tpu_available
    return 0
  fi
  rm -f .tpu_available
  MSG=$(echo "$RAW" | grep -v WARNING | tail -1 | head -c 160 | tr '"\n' "' ")
  echo "{\"ts\": \"$TS\", \"ok\": false, \"rc\": $PRC, \"msg\": \"queue probe: $MSG\"}" >> $PROBE_LOG
  return 1
}

while [ "$pos" -lt "${#QUEUE[@]}" ]; do
  if past_deadline; then
    echo "{\"ts\": \"$(date -u +%Y-%m-%dT%H:%M:%SZ)\", \"item\": \"(deadline reached — queue handed off to probe loop)\", \"rc\": 0, \"on_tpu\": false, \"attempt\": 0, \"advanced\": false, \"output\": null}" >> $QLOG
    break
  fi
  if ! probe; then
    sleep 300
    continue
  fi
  ITEM="${QUEUE[$pos]}"
  TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  OUT_FILE=$(mktemp /tmp/tpu_queue_run.XXXXXX)
  bash -c "$ITEM" > "$OUT_FILE" 2>&1
  RC=$?
  ON_TPU=false
  grep -q '"platform": "tpu"' "$OUT_FILE" && ON_TPU=true
  if [ $RC -eq 0 ] && $ON_TPU; then
    rm -f "$OUT_FILE"
    OUT_KEPT=null
  else
    # keep failed-run output for diagnosis (a skipped item's error story
    # must survive); path recorded in the log line
    OUT_KEPT="\"$OUT_FILE\""
  fi
  attempts=$((attempts + 1))
  ADV=false
  if $ON_TPU && [ $RC -eq 0 ]; then
    ADV=true
  elif [ $attempts -ge $MAX_ATTEMPTS ]; then
    ADV=true  # give up on this item; don't starve the rest
  fi
  echo "{\"ts\": \"$TS\", \"item\": \"$ITEM\", \"rc\": $RC, \"on_tpu\": $ON_TPU, \"attempt\": $attempts, \"advanced\": $ADV, \"output\": $OUT_KEPT}" >> $QLOG
  if $ADV; then
    pos=$((pos + 1))
    echo "$pos" > "$POS_FILE"
    attempts=0
  else
    sleep 60
  fi
done

# queue drained: hand back to the plain probe loop for window records
exec bash scripts/tpu_probe_loop.sh
