#!/bin/bash
# Detached TPU-backend probe: retries across the round (VERDICT round-1
# item 1), logging one JSON line per attempt to tpu_probe_log.jsonl.
# Success requires real COMPUTE (a small matmul), not just device listing —
# the axon tunnel can enumerate devices while hanging on execution.
LOG=/root/repo/tpu_probe_log.jsonl
MARK=/root/repo/.tpu_available
while true; do
  TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  RAW=$(timeout 300 python -c "
import jax, jax.numpy as jnp
ds = jax.devices()
x = jnp.ones((256, 256))
s = float((x @ x).sum())
print('PROBE_OK', ds[0].platform, len(ds), s)
" 2>&1)
  RC=$?
  OUT=$(echo "$RAW" | grep PROBE_OK | tail -1)
  if echo "$OUT" | grep -q "PROBE_OK axon\|PROBE_OK tpu"; then
    echo "{\"ts\": \"$TS\", \"ok\": true, \"probe\": \"$OUT\"}" >> $LOG
    touch $MARK
  else
    rm -f $MARK
    MSG=$(echo "$RAW" | grep -v WARNING | tail -1 | head -c 160 | tr '"\n' "' ")
    echo "{\"ts\": \"$TS\", \"ok\": false, \"rc\": $RC, \"msg\": \"$MSG\"}" >> $LOG
  fi
  sleep 480
done
