#!/usr/bin/env python
"""Corrupt-input chaos for the data plane (r10).

Generates seeded CSV / pcap / NetFlow corpora, corrupts them
deterministically, and runs FULL engine passes (source → admission →
predict → sink → commit) over the corrupt inputs.  Proof obligations:

1. **no crash** — every scenario's engine drains all batches and
   commits them (salvage degrades, never dies);
2. **byte-identical clean output** — rows untouched by corruption
   produce sink bytes identical to an uncorrupted reference run
   (admission may excise rows, never perturb survivors);
3. **every rejected row accounted for** — the row-level dead-letter
   (``<ckpt>/dead_letter_rows/``) carries exactly the corrupted rows
   (script-side corruption: count equality; SNTC_FAULTS-injected
   corruption: reference rows = sink rows + dead-lettered rows).

Scenarios:

==================  =====================================================
``csv_salvage``     K seeded corruptions (ragged line / garbage text /
                    Infinity) across a CSV corpus; salvage admission
``csv_fault_kinds`` ``source.parse`` armed with the ``ragged`` DATA kind
                    (the SNTC_FAULTS grammar path), conservation law
``pcap``            one capture truncated mid-record, one byte-flipped;
                    clean captures' flows byte-identical, truncation
                    events emitted
``netflow``         capture torn mid-datagram: record-granular tail
                    salvage, clean captures byte-identical
==================  =====================================================

Run directly (``python scripts/chaos_corrupt_corpus.py``) for a JSON
verdict; ``tests/test_admission.py`` drives the same functions in
tier-1 with a small corpus.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


# ---------------------------------------------------------------------------
# shared plumbing
# ---------------------------------------------------------------------------


def _identity():
    from sntc_tpu.core.base import Transformer

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    return Identity()


def _contract(mode: str = "salvage"):
    from sntc_tpu.data.schema import ColumnSpec, SchemaContract

    return SchemaContract(
        {"x": ColumnSpec(fill=0.0), "y": ColumnSpec(fill=0.0)}, mode=mode
    )


def sink_lines(out_dir: str) -> dict:
    """Per published batch CSV: the data lines (header stripped)."""
    out = {}
    for p in sorted(glob.glob(os.path.join(out_dir, "batch_*.csv"))):
        with open(p) as f:
            out[os.path.basename(p)] = f.read().splitlines()[1:]
    return out


def dead_letter_rows(ckpt_dir: str) -> list:
    rows = []
    pattern = os.path.join(ckpt_dir, "dead_letter_rows", "*.jsonl")
    for p in sorted(glob.glob(pattern)):
        with open(p) as f:
            rows.extend(json.loads(line) for line in f if line.strip())
    return rows


def run_csv_engine(watch: str, out: str, ckpt: str, mode: str = "salvage"):
    """One drained engine pass over a CSV dir with salvage admission
    armed; returns the query (caller inspects stats/ledgers).  No
    retry/quarantine: an unexpected error CRASHES the scenario, which
    is exactly the proof we want."""
    from sntc_tpu.serve.streaming import (
        CsvDirSink,
        FileStreamSource,
        StreamingQuery,
    )

    q = StreamingQuery(
        _identity(),
        FileStreamSource(watch, parse_salvage=True),
        CsvDirSink(out, columns=["x", "y"], durable=False),
        ckpt,
        max_batch_offsets=1,
        shape_buckets=4,
        schema_contract=_contract(mode),
    )
    q.process_available()
    return q


def write_csv_corpus(
    watch: str, n_files: int = 4, rows: int = 12, seed: int = 0
) -> list:
    """Seeded two-column float corpus; returns the per-file data lines."""
    rng = np.random.default_rng(seed)
    os.makedirs(watch, exist_ok=True)
    corpus = []
    for i in range(n_files):
        lines = [
            f"{rng.uniform(0, 100):.4f},{rng.uniform(0, 100):.4f}"
            for _ in range(rows)
        ]
        with open(os.path.join(watch, f"in_{i:03d}.csv"), "w") as f:
            f.write("x,y\n" + "\n".join(lines) + "\n")
        corpus.append(lines)
    return corpus


# ---------------------------------------------------------------------------
# scenario 1: seeded script-side corruption, exact accounting
# ---------------------------------------------------------------------------

_CSV_CORRUPTIONS = ("ragged", "garbage", "infinity")


def corrupt_csv_corpus(
    watch: str, corpus: list, n_corrupt: int, seed: int
) -> set:
    """Corrupt ``n_corrupt`` distinct data rows in place (seeded),
    rotating through ragged / garbage-text / Infinity; returns the
    corrupted ``(file_idx, row_idx)`` set."""
    rng = np.random.default_rng(seed + 1)
    n_files, rows = len(corpus), len(corpus[0])
    picks: set = set()
    while len(picks) < n_corrupt:
        picks.add(
            (int(rng.integers(0, n_files)), int(rng.integers(0, rows)))
        )
    for k, (fi, ri) in enumerate(sorted(picks)):
        lines = list(corpus[fi])
        kind = _CSV_CORRUPTIONS[k % len(_CSV_CORRUPTIONS)]
        if kind == "ragged":
            lines[ri] = lines[ri] + ",999999"  # wrong field count
        elif kind == "garbage":
            x = lines[ri].split(",")[0]
            lines[ri] = f"{x},@@not-a-number@@"
        else:  # infinity
            x = lines[ri].split(",")[0]
            lines[ri] = f"{x},Infinity"
        corpus[fi] = lines
        with open(os.path.join(watch, f"in_{fi:03d}.csv"), "w") as f:
            f.write("x,y\n" + "\n".join(lines) + "\n")
    return picks


def scenario_csv_salvage(
    workdir: str, n_files: int = 4, rows: int = 12, n_corrupt: int = 7,
    seed: int = 0,
) -> dict:
    """K seeded corruptions; prove no crash + byte-identical survivors
    + dead-letter count == K."""
    import sntc_tpu.resilience as R

    R.clear()
    ref_d = os.path.join(workdir, "csv_ref")
    cor_d = os.path.join(workdir, "csv_corrupt")
    ref_corpus = write_csv_corpus(
        os.path.join(ref_d, "in"), n_files, rows, seed
    )
    cor_corpus = write_csv_corpus(
        os.path.join(cor_d, "in"), n_files, rows, seed
    )
    run_csv_engine(
        os.path.join(ref_d, "in"), os.path.join(ref_d, "out"),
        os.path.join(ref_d, "ckpt"),
    )
    picks = corrupt_csv_corpus(
        os.path.join(cor_d, "in"), cor_corpus, n_corrupt, seed
    )
    q = run_csv_engine(
        os.path.join(cor_d, "in"), os.path.join(cor_d, "out"),
        os.path.join(cor_d, "ckpt"),
    )

    ref_lines = sink_lines(os.path.join(ref_d, "out"))
    got_lines = sink_lines(os.path.join(cor_d, "out"))
    # expected = the reference output minus exactly the corrupted rows
    expect = {}
    for fi, name in enumerate(sorted(ref_lines)):
        expect[name] = [
            line
            for ri, line in enumerate(ref_lines[name])
            if (fi, ri) not in picks
        ]
    dead = dead_letter_rows(os.path.join(cor_d, "ckpt"))
    committed = q.last_committed() + 1
    ok = (
        committed == n_files
        and got_lines == expect
        and len(dead) == n_corrupt
        # salvage must never change a dispatched shape: every batch has
        # `rows` input rows -> one bucket -> exactly ONE compile event
        and q.predictor.compile_events == 1
    )
    return {
        "scenario": "csv_salvage", "ok": bool(ok),
        "committed": committed, "expected_batches": n_files,
        "corrupted": len(picks), "dead_letter_rows": len(dead),
        "compile_events": q.predictor.compile_events,
        "sink_match": got_lines == expect,
        "admission": q.admission_stats(),
        "reasons": sorted({r["reason"] for r in dead}),
    }


# ---------------------------------------------------------------------------
# scenario 2: the SNTC_FAULTS grammar path (ragged DATA kind)
# ---------------------------------------------------------------------------


def scenario_csv_fault_kinds(
    workdir: str, n_files: int = 6, rows: int = 10, seed: int = 7,
) -> dict:
    """Arm ``source.parse`` with the ``ragged`` DATA kind (prob 0.5,
    seeded — the ``SNTC_FAULTS=source.parse:ragged:0.5:<seed>`` path)
    and prove the conservation law: reference rows = sink rows +
    dead-lettered rows, zero crashes."""
    import sntc_tpu.resilience as R

    R.clear()
    d = os.path.join(workdir, "csv_faults")
    write_csv_corpus(os.path.join(d, "in"), n_files, rows, seed)
    total_rows = n_files * rows
    R.arm("source.parse", kind="ragged", prob=0.5, seed=seed, times=None)
    try:
        q = run_csv_engine(
            os.path.join(d, "in"), os.path.join(d, "out"),
            os.path.join(d, "ckpt"),
        )
    finally:
        R.clear()
    got = sum(len(v) for v in sink_lines(os.path.join(d, "out")).values())
    dead = dead_letter_rows(os.path.join(d, "ckpt"))
    committed = q.last_committed() + 1
    ok = committed == n_files and got + len(dead) == total_rows
    return {
        "scenario": "csv_fault_kinds", "ok": bool(ok),
        "committed": committed, "expected_batches": n_files,
        "reference_rows": total_rows, "sink_rows": got,
        "dead_letter_rows": len(dead),
        "faults_injected": sum(
            1 for e in R.recent_events()
            if e.get("event") == "fault_injected"
        ),
    }


# ---------------------------------------------------------------------------
# scenario 3 & 4: binary captures (pcap / netflow)
# ---------------------------------------------------------------------------


def _run_capture_engine(source, out: str, ckpt: str):
    from sntc_tpu.serve.streaming import CsvDirSink, StreamingQuery

    q = StreamingQuery(
        _identity(), source, CsvDirSink(out, durable=False), ckpt,
        max_batch_offsets=1,
    )
    q.process_available()
    return q


def scenario_pcap(workdir: str, n_files: int = 3, seed: int = 3) -> dict:
    """Truncate one capture mid-record and byte-flip another; prove the
    engine drains every batch, clean captures' flow output is
    byte-identical, and truncation surfaced as structured events."""
    import sntc_tpu.resilience as R
    from sntc_tpu.native.pcap import make_packet, make_pcap
    from sntc_tpu.serve.netflow_source import PcapDirSource

    R.clear()
    R.clear_events()
    rng = np.random.default_rng(seed)
    caps = []
    for i in range(n_files):
        pkts = [
            (
                1000.0 + i + p * 0.01,
                make_packet(
                    int(rng.integers(1, 2**31)), int(rng.integers(1, 2**31)),
                    int(rng.integers(1, 65000)), 80,
                    payload=int(rng.integers(10, 200)),
                ),
            )
            for p in range(8)
        ]
        caps.append(make_pcap(pkts))

    def _write(d, blobs):
        os.makedirs(d, exist_ok=True)
        for i, blob in enumerate(blobs):
            with open(os.path.join(d, f"cap_{i:03d}.pcap"), "wb") as f:
                f.write(blob)

    ref_d = os.path.join(workdir, "pcap_ref")
    cor_d = os.path.join(workdir, "pcap_corrupt")
    _write(os.path.join(ref_d, "in"), caps)
    corrupted = list(caps)
    corrupted[1] = caps[1][: len(caps[1]) - 37]  # torn mid-record
    flipped = bytearray(caps[2])
    for pos in rng.integers(24, len(flipped), size=8):
        flipped[int(pos)] ^= 0xFF
    corrupted[2] = bytes(flipped)
    _write(os.path.join(cor_d, "in"), corrupted)

    _run_capture_engine(
        PcapDirSource(os.path.join(ref_d, "in")),
        os.path.join(ref_d, "out"), os.path.join(ref_d, "ckpt"),
    )
    q = _run_capture_engine(
        PcapDirSource(os.path.join(cor_d, "in")),
        os.path.join(cor_d, "out"), os.path.join(cor_d, "ckpt"),
    )
    ref = sink_lines(os.path.join(ref_d, "out"))
    got = sink_lines(os.path.join(cor_d, "out"))
    clean = "batch_000000.csv"  # file 0 untouched
    truncation_events = [
        e for e in R.recent_events()
        if e.get("event") == "parse_truncated" and e.get("format") == "pcap"
    ]
    committed = q.last_committed() + 1
    ok = (
        committed == n_files
        and got.get(clean) == ref.get(clean)
        and len(truncation_events) >= 1
    )
    return {
        "scenario": "pcap", "ok": bool(ok), "committed": committed,
        "expected_batches": n_files,
        "clean_capture_match": got.get(clean) == ref.get(clean),
        "truncation_events": len(truncation_events),
    }


def scenario_netflow(workdir: str, n_files: int = 3, seed: int = 5) -> dict:
    """Tear one capture mid-datagram; prove record-granular tail
    salvage (expected record count survives), clean captures
    byte-identical, zero crashes."""
    import sntc_tpu.resilience as R
    from sntc_tpu.native.netflow import make_datagram
    from sntc_tpu.serve.netflow_source import NetFlowDirSource

    R.clear()
    R.clear_events()
    rng = np.random.default_rng(seed)

    def _records(n):
        out = []
        for _ in range(n):
            first = int(rng.integers(0, 1_000_000))
            out.append((
                int(rng.integers(0, 2**32)), int(rng.integers(0, 2**32)),
                int(rng.integers(0, 65536)), int(rng.integers(0, 65536)),
                6, 0x18, 0, int(rng.integers(1, 1000)),
                int(rng.integers(40, 100_000)), first,
                first + int(rng.integers(0, 60_000)), 1, 2, 0, 0,
            ))
        return out

    blobs = [
        make_datagram(_records(6), seq=i) + make_datagram(_records(4), seq=i)
        for i in range(n_files)
    ]

    def _write(d, payloads):
        os.makedirs(d, exist_ok=True)
        for i, blob in enumerate(payloads):
            with open(os.path.join(d, f"cap_{i:03d}.nf5"), "wb") as f:
                f.write(blob)

    ref_d = os.path.join(workdir, "nf_ref")
    cor_d = os.path.join(workdir, "nf_corrupt")
    _write(os.path.join(ref_d, "in"), blobs)
    corrupted = list(blobs)
    # tear the SECOND datagram of file 1 mid-record: 2 of its 4 records
    # fit -> 6 + 2 rows survive at record granularity
    torn_at = len(make_datagram([])) + 6 * 48 + (24 + 2 * 48 + 17)
    corrupted[1] = blobs[1][:torn_at]
    _write(os.path.join(cor_d, "in"), corrupted)

    _run_capture_engine(
        NetFlowDirSource(os.path.join(ref_d, "in")),
        os.path.join(ref_d, "out"), os.path.join(ref_d, "ckpt"),
    )
    q = _run_capture_engine(
        NetFlowDirSource(os.path.join(cor_d, "in")),
        os.path.join(cor_d, "out"), os.path.join(cor_d, "ckpt"),
    )
    ref = sink_lines(os.path.join(ref_d, "out"))
    got = sink_lines(os.path.join(cor_d, "out"))
    clean = [f"batch_{i:06d}.csv" for i in (0, 2)]
    torn = "batch_000001.csv"
    truncation_events = [
        e for e in R.recent_events()
        if e.get("event") == "parse_truncated"
        and e.get("format") == "netflow"
    ]
    committed = q.last_committed() + 1
    ok = (
        committed == n_files
        and all(got.get(c) == ref.get(c) for c in clean)
        and len(got.get(torn, [])) == 6 + 2  # record-granular salvage
        # the surviving prefix rows are byte-identical too
        and got.get(torn, []) == ref.get(torn, [])[: 6 + 2]
        and len(truncation_events) >= 1
    )
    return {
        "scenario": "netflow", "ok": bool(ok), "committed": committed,
        "expected_batches": n_files,
        "clean_capture_match": all(
            got.get(c) == ref.get(c) for c in clean
        ),
        "torn_rows": len(got.get(torn, [])),
        "expected_torn_rows": 8,
        "truncation_events": len(truncation_events),
    }


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def run_all(workdir: str, seed: int = 0) -> dict:
    results = [
        scenario_csv_salvage(workdir, seed=seed),
        scenario_csv_fault_kinds(workdir, seed=seed + 7),
        scenario_pcap(workdir, seed=seed + 3),
        scenario_netflow(workdir, seed=seed + 5),
    ]
    return {"ok": all(r["ok"] for r in results), "scenarios": results}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    workdir = args.workdir
    if workdir is None:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="chaos_corrupt_")
    verdict = run_all(workdir, seed=args.seed)
    print(json.dumps(verdict, indent=1))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
