#!/usr/bin/env python
"""Static drift check: fault sites in code ⇔ docs/RESILIENCE.md.

Every ``fault_point("<site>")`` call site wired in ``sntc_tpu/`` must
be (a) declared in ``sntc_tpu.resilience.SITES`` and (b) documented in
the site table of ``docs/RESILIENCE.md`` — and vice versa: a
documented or declared site with no live call site is drift too.
Wired as a tier-1 test (``tests/test_supervision.py``) so the three
sources cannot diverge silently.

Exit 0 when consistent; exit 1 with a per-direction report otherwise.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CALL_RE = re.compile(r"""fault_point\(\s*["']([A-Za-z0-9_.]+)["']\s*\)""")
# docs table rows: | `site.name` | description |
_DOC_RE = re.compile(r"^\|\s*`([A-Za-z0-9_.]+)`\s*\|", re.MULTILINE)


def code_sites(root: str = None) -> set:
    """Sites passed as literals to fault_point() anywhere in sntc_tpu/
    (the definition module itself is excluded — it is the hook, not a
    call site)."""
    root = root or os.path.join(REPO, "sntc_tpu")
    sites = set()
    for dirpath, _, files in os.walk(root):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            if path.endswith(os.path.join("resilience", "faults.py")):
                continue
            with open(path) as f:
                sites.update(_CALL_RE.findall(f.read()))
    return sites


def declared_sites() -> set:
    sys.path.insert(0, REPO)
    from sntc_tpu.resilience import SITES

    return set(SITES)


def documented_sites(doc_path: str = None) -> set:
    doc_path = doc_path or os.path.join(REPO, "docs", "RESILIENCE.md")
    with open(doc_path) as f:
        text = f.read()
    return {s for s in _DOC_RE.findall(text) if "." in s and s != "site"}


def check() -> list:
    """Returns a list of human-readable drift complaints (empty = ok)."""
    in_code = code_sites()
    declared = declared_sites()
    documented = documented_sites()
    problems = []
    for site in sorted(in_code - declared):
        problems.append(
            f"fault_point({site!r}) is wired in code but missing from "
            "sntc_tpu.resilience.SITES"
        )
    for site in sorted(in_code - documented):
        problems.append(
            f"fault_point({site!r}) is wired in code but undocumented "
            "in docs/RESILIENCE.md"
        )
    for site in sorted(declared - in_code):
        problems.append(
            f"SITES declares {site!r} but no fault_point({site!r}) call "
            "site exists in sntc_tpu/"
        )
    for site in sorted(documented - in_code):
        problems.append(
            f"docs/RESILIENCE.md documents {site!r} but no "
            f"fault_point({site!r}) call site exists in sntc_tpu/"
        )
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("fault-site drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    n = len(code_sites())
    print(f"ok: {n} fault sites consistent across code, SITES, and docs")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
