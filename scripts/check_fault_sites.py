#!/usr/bin/env python
"""Static drift check: fault sites AND kinds in code ⇔ docs/RESILIENCE.md.

Every ``fault_point("<site>")`` / ``fault_data("<site>", ...)`` call
site wired in ``sntc_tpu/`` must be (a) declared in
``sntc_tpu.resilience.SITES`` and (b) documented in the site table of
``docs/RESILIENCE.md`` — and vice versa: a documented or declared site
with no live call site is drift too.  The SNTC_FAULTS *kind*
vocabulary (``sntc_tpu.resilience.ALL_KINDS`` — exc/io/timeout/kill
plus the r10 data-corruption kinds corrupt_bytes/truncate/ragged) must
likewise match the marker-delimited kinds table in the docs.  Wired as
a tier-1 test (``tests/test_supervision.py``) so code, grammar, and
docs cannot diverge silently.

Exit 0 when consistent; exit 1 with a per-direction report otherwise.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CALL_RE = re.compile(
    r"""fault_(?:point|data)\(\s*["']([A-Za-z0-9_.]+)["']"""
)
# the r17 storage sites are injected via fault_disk inside the storage
# plane's write helpers; the helpers take the site as a kwarg, so the
# literal at the CALL site is ``site="storage.<artifact>"`` (or a
# direct fault_disk("storage.…") call)
_DISK_RE = re.compile(
    r"""(?:fault_disk\(\s*|site(?:\s*:\s*str)?\s*=\s*)"""
    r"""["'](storage\.[A-Za-z0-9_.]+)["']"""
)
# docs table rows: | `site.name` | description |
_DOC_RE = re.compile(r"^\|\s*`([A-Za-z0-9_.]+)`\s*\|", re.MULTILINE)
# the kinds table lives between these markers in docs/RESILIENCE.md
_KINDS_BEGIN = "<!-- fault-kinds:begin -->"
_KINDS_END = "<!-- fault-kinds:end -->"
_KIND_ROW_RE = re.compile(r"^\|\s*`([A-Za-z0-9_]+)`\s*\|", re.MULTILINE)


def code_sites(root: str = None) -> set:
    """Sites passed as literals to fault_point() anywhere in sntc_tpu/
    (the definition module itself is excluded — it is the hook, not a
    call site)."""
    root = root or os.path.join(REPO, "sntc_tpu")
    sites = set()
    for dirpath, _, files in os.walk(root):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            if path.endswith(os.path.join("resilience", "faults.py")):
                continue
            with open(path) as f:
                text = f.read()
            sites.update(_CALL_RE.findall(text))
            sites.update(_DISK_RE.findall(text))
    return sites


def declared_sites() -> set:
    sys.path.insert(0, REPO)
    from sntc_tpu.resilience import SITES

    return set(SITES)


def documented_sites(doc_path: str = None) -> set:
    doc_path = doc_path or os.path.join(REPO, "docs", "RESILIENCE.md")
    with open(doc_path) as f:
        text = f.read()
    return {s for s in _DOC_RE.findall(text) if "." in s and s != "site"}


def declared_kinds() -> set:
    sys.path.insert(0, REPO)
    from sntc_tpu.resilience import ALL_KINDS

    return set(ALL_KINDS)


def documented_kinds(doc_path: str = None) -> set:
    doc_path = doc_path or os.path.join(REPO, "docs", "RESILIENCE.md")
    with open(doc_path) as f:
        text = f.read()
    if _KINDS_BEGIN not in text or _KINDS_END not in text:
        return set()  # reported as a drift problem by check()
    table = text.split(_KINDS_BEGIN, 1)[1].split(_KINDS_END, 1)[0]
    return {k for k in _KIND_ROW_RE.findall(table) if k != "kind"}


def check_kinds() -> list:
    """Kind-vocabulary drift complaints (empty = ok)."""
    declared = declared_kinds()
    documented = documented_kinds()
    if not documented:
        return [
            "docs/RESILIENCE.md is missing the marker-delimited fault-"
            f"kinds table ({_KINDS_BEGIN} ... {_KINDS_END})"
        ]
    problems = []
    for kind in sorted(declared - documented):
        problems.append(
            f"fault kind {kind!r} is in sntc_tpu.resilience.ALL_KINDS "
            "but missing from the docs/RESILIENCE.md kinds table"
        )
    for kind in sorted(documented - declared):
        problems.append(
            f"docs/RESILIENCE.md kinds table documents {kind!r} but the "
            "SNTC_FAULTS grammar (ALL_KINDS) does not accept it"
        )
    return problems


_CHAOS = "scripts/chaos_crash_matrix.py"
# the kill-site tuples the crash matrix drives; every stream.*/sink.*,
# every flow.*, every ctl.*, every device.*, every fleet.* site — and
# every *.compile site (the r18 compute-plane boundaries) — must
# appear in one of them
_CHAOS_TUPLE_RE = re.compile(
    r"^(?:KILL_SITES|FLOW_KILL_SITES|CTL_KILL_SITES|DEVICE_KILL_SITES"
    r"|FLEET_KILL_SITES|INGRESS_KILL_SITES|REPL_KILL_SITES)"
    r"\s*=\s*\(([^)]*)\)",
    re.MULTILINE,
)


def chaos_kill_sites() -> set:
    """Sites the chaos crash matrix kills at (KILL_SITES +
    FLOW_KILL_SITES literals in the script)."""
    with open(os.path.join(REPO, _CHAOS)) as f:
        text = f.read()
    sites = set()
    for body in _CHAOS_TUPLE_RE.findall(text):
        sites.update(re.findall(r"""["']([A-Za-z0-9_.]+)["']""", body))
    return sites


def check_chaos_coverage() -> list:
    """Every engine-protocol fault site (stream.*/sink.*/flow.*/
    device.*) and every *.compile site must have a kill-and-restart
    scenario in the crash matrix — a declared site nobody ever kills
    at is untested crash surface."""
    covered = chaos_kill_sites()
    must_cover = {
        s for s in declared_sites()
        if (
            s.split(".")[0] in ("stream", "sink", "flow", "ctl",
                                "device", "fleet", "ingress", "repl")
            or s.endswith(".compile")
        )
        and s != "stream.read"  # read kills pre-WAL == stream.wal row
    }
    return [
        f"fault site {site!r} has no kill scenario in {_CHAOS} "
        "(KILL_SITES/FLOW_KILL_SITES/DEVICE_KILL_SITES)"
        for site in sorted(must_cover - covered)
    ]


def check() -> list:
    """Returns a list of human-readable drift complaints (empty = ok)."""
    in_code = code_sites()
    declared = declared_sites()
    documented = documented_sites()
    problems = []
    for site in sorted(in_code - declared):
        problems.append(
            f"fault_point({site!r}) is wired in code but missing from "
            "sntc_tpu.resilience.SITES"
        )
    for site in sorted(in_code - documented):
        problems.append(
            f"fault_point({site!r}) is wired in code but undocumented "
            "in docs/RESILIENCE.md"
        )
    for site in sorted(declared - in_code):
        problems.append(
            f"SITES declares {site!r} but no fault_point({site!r}) call "
            "site exists in sntc_tpu/"
        )
    for site in sorted(documented - in_code):
        problems.append(
            f"docs/RESILIENCE.md documents {site!r} but no "
            f"fault_point({site!r}) call site exists in sntc_tpu/"
        )
    problems.extend(check_kinds())
    problems.extend(check_chaos_coverage())
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("fault-site drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    n = len(code_sites())
    k = len(declared_kinds())
    print(
        f"ok: {n} fault sites and {k} kinds consistent across code, "
        "SITES/ALL_KINDS, and docs"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
