#!/usr/bin/env python
"""Static drift check: model-lifecycle knobs across CLI ⇔ lifecycle ⇔ docs.

The live-model lifecycle surface is one feature spread over three
layers — ``python -m sntc_tpu serve`` flags, the
``sntc_tpu.lifecycle`` constructor kwargs/methods they map to, and the
documentation — and each knob must exist in all of them:

====================  ==============================================
``--partial-fit``     ``LifecycleManager(partial_fit=...)``
``--drift-window``    ``DriftMonitor(window=...)``
``--drift-threshold`` ``DriftMonitor(threshold=...)``
``--promote-from``    ``ModelPromoter.load_candidate(...)``
``--shadow-window``   ``ModelPromoter(window=...)``
====================  ==============================================

Every flag must appear in ``docs/RESILIENCE.md`` AND the README serve
section.  Wired as a tier-1 test (``tests/test_lifecycle.py``) so the
three layers cannot drift silently — the ``check_perf_flags.py``
discipline applied to the lifecycle surface.

Exit 0 when consistent; exit 1 with a per-knob report otherwise.
"""

from __future__ import annotations

import inspect
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (CLI flag, owner class name, kwarg-or-method it maps to)
FLAGS = (
    ("--partial-fit", "LifecycleManager", "partial_fit"),
    ("--drift-window", "DriftMonitor", "window"),
    ("--drift-threshold", "DriftMonitor", "threshold"),
    ("--promote-from", "ModelPromoter", "load_candidate"),
    ("--shadow-window", "ModelPromoter", "window"),
    ("--promote-margin", "ModelPromoter", "margin"),
)
DOCS = ("docs/RESILIENCE.md", "README.md")


def _read(rel: str) -> str:
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _owner(name: str):
    sys.path.insert(0, REPO)
    from sntc_tpu.lifecycle import (
        DriftMonitor,
        LifecycleManager,
        ModelPromoter,
    )

    return {
        "LifecycleManager": LifecycleManager,
        "DriftMonitor": DriftMonitor,
        "ModelPromoter": ModelPromoter,
    }[name]


def check() -> list:
    """Returns a list of human-readable drift complaints (empty = ok)."""
    problems = []
    app_src = _read(os.path.join("sntc_tpu", "app.py"))
    doc_srcs = {rel: _read(rel) for rel in DOCS}
    for flag, owner_name, target in FLAGS:
        if f'"{flag}"' not in app_src:
            problems.append(
                f"serve CLI flag {flag!r} missing from sntc_tpu/app.py"
            )
        owner = _owner(owner_name)
        params = inspect.signature(owner.__init__).parameters
        if target not in params and not callable(
            getattr(owner, target, None)
        ):
            problems.append(
                f"{owner_name} has neither a {target!r} kwarg nor a "
                f"{target!r} method for {flag!r} to map to"
            )
        for rel, src in doc_srcs.items():
            if flag not in src:
                problems.append(f"{flag!r} undocumented in {rel}")
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("lifecycle-flag drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {len(FLAGS)} lifecycle flags consistent across CLI, "
        "lifecycle kwargs, and docs"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
