#!/usr/bin/env python
"""Static drift check: fusion capability ⇔ documentation.

The whole-pipeline fusion compiler (``sntc_tpu/fuse/``) fuses exactly
the feature transformers whose classes register a device-fn builder in
``sntc_tpu.fuse.registry``.  Every OTHER feature transformer silently
falls back to its eager ``transform`` — which is correct, but must be a
DOCUMENTED decision, not drift: a new stage added without either a
registration or a docs entry would quietly serve slower forever.

This script asserts that every ``Transformer`` exported by
``sntc_tpu.feature`` (fitted models included, estimators excluded) is in
exactly one of:

* the capability registry (``registered_types()``), or
* the "deliberately non-fusible stages" table of
  ``docs/PERFORMANCE.md`` (a ``| `ClassName` | reason |`` row).

and, symmetrically, that the docs table names no class that is in fact
registered (stale row) or does not exist (typo).  Wired as a tier-1
test (``tests/test_fuse_pipeline.py``) — the ``check_fault_sites.py`` /
``check_perf_flags.py`` discipline applied to the fusion surface.

Exit 0 when consistent; exit 1 with a per-class report otherwise.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = "docs/PERFORMANCE.md"
TABLE_START = "<!-- non-fusible-stages -->"
TABLE_END = "<!-- /non-fusible-stages -->"


def _doc_table_names() -> set:
    with open(os.path.join(REPO, DOC)) as f:
        src = f.read()
    if TABLE_START not in src or TABLE_END not in src:
        raise SystemExit(
            f"{DOC} lacks the {TABLE_START} … {TABLE_END} markers around "
            "the non-fusible-stages table"
        )
    table = src.split(TABLE_START, 1)[1].split(TABLE_END, 1)[0]
    return set(re.findall(r"^\|\s*`(\w+)`", table, flags=re.MULTILINE))


def check() -> list:
    """Returns a list of human-readable drift complaints (empty = ok)."""
    sys.path.insert(0, REPO)
    import sntc_tpu.feature as feature
    from sntc_tpu.core.base import Estimator, Transformer
    from sntc_tpu.fuse import registered_types

    transformers = {
        name
        for name in feature.__all__
        if isinstance(cls := getattr(feature, name), type)
        and issubclass(cls, Transformer)
        and not issubclass(cls, Estimator)
    }
    registered = {cls.__name__ for cls in registered_types()}
    documented = _doc_table_names()

    problems = []
    for name in sorted(transformers - registered - documented):
        problems.append(
            f"{name}: neither registers a device_fn "
            "(sntc_tpu.fuse.registry) nor appears in the non-fusible "
            f"table of {DOC}"
        )
    for name in sorted(documented & registered):
        problems.append(
            f"{name}: listed as non-fusible in {DOC} but registers a "
            "device_fn — stale docs row"
        )
    for name in sorted(documented - transformers):
        problems.append(
            f"{name}: in the {DOC} non-fusible table but not exported "
            "by sntc_tpu.feature — typo or removed stage"
        )
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("fusible-stage drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    sys.path.insert(0, REPO)
    from sntc_tpu.fuse import registered_types

    print(
        f"ok: {len(registered_types())} device-fn registrations and the "
        f"{DOC} non-fusible table cover every feature transformer"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
