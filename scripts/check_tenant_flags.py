#!/usr/bin/env python
"""Static drift check: multi-tenant knobs across CLI ⇔ TenantSpec ⇔ docs.

The multi-tenant serving surface is one feature spread over three
layers — ``python -m sntc_tpu serve-daemon`` flags (daemon-level
defaults), the :class:`sntc_tpu.serve.tenancy.TenantSpec` fields they
fill (each overridable per tenant in the ``--tenants`` JSON file), and
the documentation — and each knob must exist in all of them:

======================== ==============================
``--tenant-weight``      ``TenantSpec.weight``
``--max-rows-per-sec``   ``TenantSpec.max_rows_per_sec``
``--max-pending-batches````TenantSpec.max_pending_batches``
``--shed-policy``        ``TenantSpec.shed_policy``
``--quarantine-after``   ``TenantSpec.quarantine_after``
``--quarantine-cooldown````TenantSpec.quarantine_cooldown_s``
``--stop-after``         ``TenantSpec.stop_after``
``--row-policy``         ``TenantSpec.row_policy``
``--max-files-per-batch````TenantSpec.max_batch_offsets``
``--max-batch-failures`` ``TenantSpec.max_batch_failures``
======================== ==============================

Every flag AND its spec field must appear in the marker-delimited
tenant-flags table of ``docs/RESILIENCE.md``, and the serve-daemon
quickstart must exist in the README.  Wired as a tier-1 test
(``tests/test_tenancy.py``) so the three layers cannot drift silently
— the ``check_lifecycle_flags.py`` discipline applied to the tenancy
surface.

Exit 0 when consistent; exit 1 with a per-knob report otherwise.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (serve-daemon CLI flag, TenantSpec field it defaults)
FLAGS = (
    ("--tenant-weight", "weight"),
    ("--max-rows-per-sec", "max_rows_per_sec"),
    ("--max-pending-batches", "max_pending_batches"),
    ("--shed-policy", "shed_policy"),
    ("--quarantine-after", "quarantine_after"),
    ("--quarantine-cooldown", "quarantine_cooldown_s"),
    ("--stop-after", "stop_after"),
    ("--row-policy", "row_policy"),
    ("--max-files-per-batch", "max_batch_offsets"),
    ("--max-batch-failures", "max_batch_failures"),
    ("--disk-budget-mb", "disk_budget_mb"),
)
DOC = "docs/RESILIENCE.md"
TABLE_BEGIN = "<!-- tenant-flags:begin -->"
TABLE_END = "<!-- tenant-flags:end -->"
README_NEEDLE = "serve-daemon"


def _read(rel: str) -> str:
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _doc_table() -> str:
    text = _read(DOC)
    if TABLE_BEGIN not in text or TABLE_END not in text:
        return ""
    return text.split(TABLE_BEGIN, 1)[1].split(TABLE_END, 1)[0]


def check() -> list:
    """Returns a list of human-readable drift complaints (empty = ok)."""
    problems = []
    app_src = _read(os.path.join("sntc_tpu", "app.py"))
    # flags must be declared on the shared daemon_flags parent parser
    # (r19: serve-daemon and fleet-serve both inherit the whole
    # daemon flag surface from it)
    daemon_src = app_src.split("p = daemon_flags = ", 1)
    daemon_src = daemon_src[1] if len(daemon_src) == 2 else ""
    sys.path.insert(0, REPO)
    from dataclasses import fields as dc_fields

    from sntc_tpu.serve.tenancy import TenantSpec

    spec_fields = {f.name for f in dc_fields(TenantSpec)}
    table = _doc_table()
    if not table:
        problems.append(
            f"{DOC} is missing the marker-delimited tenant-flags table "
            f"({TABLE_BEGIN} ... {TABLE_END})"
        )
    for flag, fld in FLAGS:
        if f'"{flag}"' not in daemon_src:
            problems.append(
                f"serve-daemon CLI flag {flag!r} missing from the "
                "serve-daemon parser in sntc_tpu/app.py"
            )
        if fld not in spec_fields:
            problems.append(
                f"TenantSpec has no {fld!r} field for {flag!r} to "
                "default"
            )
        if table and (flag not in table or f"`{fld}`" not in table):
            problems.append(
                f"{flag!r} / field {fld!r} missing from the {DOC} "
                "tenant-flags table"
            )
    # the reverse direction: every table row must be a known flag
    for row_flag in re.findall(r"`(--[a-z-]+)`", table):
        if row_flag not in {f for f, _ in FLAGS}:
            problems.append(
                f"{DOC} tenant-flags table documents {row_flag!r} but "
                "the checker's FLAGS mapping does not declare it"
            )
    if README_NEEDLE not in _read("README.md"):
        problems.append("README.md has no serve-daemon quickstart")
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("tenant-flag drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {len(FLAGS)} tenant flags consistent across the "
        "serve-daemon CLI, TenantSpec fields, and docs"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
