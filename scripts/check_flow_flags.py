#!/usr/bin/env python
"""Static drift check: raw-capture flow knobs across CLI ⇔ flow ⇔ docs.

The stateful flow-window surface is one feature spread over three
layers — ``python -m sntc_tpu serve`` flags, the ``sntc_tpu.flow``
constructor kwargs they map to, and the documentation — and each knob
must exist in all of them:

==========================  =========================================
``--from-capture``          ``FlowCaptureSource(format=...)``
``--flow-timeout``          ``PcapFlowMeter(flow_timeout=...)``
``--flow-activity-timeout`` ``PcapFlowMeter(activity_timeout=...)``
``--flow-lateness``         ``FlowFeatureEngine(allowed_lateness=...)``
``--flow-max-packets``      ``FlowFeatureEngine(max_state_packets=...)``
==========================  =========================================

Every flag must appear in the marker-delimited flow-flags table of
``docs/RESILIENCE.md`` AND in the README raw-capture quickstart, and
the serve-daemon parser must carry the ``--from-capture`` default for
the matching ``TenantSpec.from_capture`` field.  Wired as a tier-1
test (``tests/test_flow.py``) so the layers cannot drift silently —
the ``check_lifecycle_flags.py`` discipline applied to the flow
surface.

Exit 0 when consistent; exit 1 with a per-knob report otherwise.
"""

from __future__ import annotations

import inspect
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (serve CLI flag, owner class name, kwarg it maps to)
FLAGS = (
    ("--from-capture", "FlowCaptureSource", "format"),
    ("--flow-timeout", "PcapFlowMeter", "flow_timeout"),
    ("--flow-activity-timeout", "PcapFlowMeter", "activity_timeout"),
    ("--flow-lateness", "FlowFeatureEngine", "allowed_lateness"),
    ("--flow-max-packets", "FlowFeatureEngine", "max_state_packets"),
)
DOC = "docs/RESILIENCE.md"
TABLE_BEGIN = "<!-- flow-flags:begin -->"
TABLE_END = "<!-- flow-flags:end -->"
README_NEEDLE = "--from-capture"


def _read(rel: str) -> str:
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _owner(name: str):
    sys.path.insert(0, REPO)
    from sntc_tpu.flow import (
        FlowCaptureSource,
        FlowFeatureEngine,
        PcapFlowMeter,
    )

    return {
        "FlowCaptureSource": FlowCaptureSource,
        "FlowFeatureEngine": FlowFeatureEngine,
        "PcapFlowMeter": PcapFlowMeter,
    }[name]


def _doc_table() -> str:
    text = _read(DOC)
    if TABLE_BEGIN not in text or TABLE_END not in text:
        return ""
    return text.split(TABLE_BEGIN, 1)[1].split(TABLE_END, 1)[0]


def check() -> list:
    """Returns a list of human-readable drift complaints (empty = ok)."""
    problems = []
    app_src = _read(os.path.join("sntc_tpu", "app.py"))
    table = _doc_table()
    if not table:
        problems.append(
            f"{DOC} is missing the marker-delimited flow-flags table "
            f"({TABLE_BEGIN} ... {TABLE_END})"
        )
    if README_NEEDLE not in _read("README.md"):
        problems.append(
            "README.md has no raw-capture quickstart "
            f"({README_NEEDLE!r} not found)"
        )
    for flag, owner_name, target in FLAGS:
        if f'"{flag}"' not in app_src:
            problems.append(
                f"serve CLI flag {flag!r} missing from sntc_tpu/app.py"
            )
        owner = _owner(owner_name)
        params = inspect.signature(owner.__init__).parameters
        if target not in params:
            problems.append(
                f"{owner_name} has no {target!r} kwarg for {flag!r} "
                "to map to"
            )
        if table and flag not in table:
            problems.append(
                f"{flag!r} missing from the {DOC} flow-flags table"
            )
    # reverse direction: every table row must be a declared flag
    for row_flag in re.findall(r"`(--[a-z-]+)`", table):
        if row_flag not in {f for f, _o, _t in FLAGS}:
            problems.append(
                f"{DOC} flow-flags table documents {row_flag!r} but "
                "the checker's FLAGS mapping does not declare it"
            )
    # the daemon side: the per-tenant default flag and its spec field.
    # the flag lives on the shared daemon_flags parent parser (r19:
    # serve-daemon and fleet-serve both inherit it)
    daemon_src = app_src.split("p = daemon_flags = ", 1)
    daemon_src = daemon_src[1] if len(daemon_src) == 2 else ""
    if '"--from-capture"' not in daemon_src:
        problems.append(
            "daemon_flags parent parser is missing the "
            "'--from-capture' per-tenant default flag"
        )
    from dataclasses import fields as dc_fields

    sys.path.insert(0, REPO)
    from sntc_tpu.serve.tenancy import TenantSpec

    spec_fields = {f.name for f in dc_fields(TenantSpec)}
    for fld in ("from_capture", "flow_options"):
        if fld not in spec_fields:
            problems.append(
                f"TenantSpec has no {fld!r} field for the daemon "
                "raw-capture surface"
            )
    return sorted(set(problems))


def main() -> int:
    problems = check()
    if problems:
        print("flow-flag drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {len(FLAGS)} flow flags consistent across CLI, flow "
        "kwargs, TenantSpec, and docs"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
