#!/usr/bin/env python
"""Static drift check: warm-standby replication surface across CLI ⇔
ReplicationPlane ⇔ metric catalog ⇔ docs.

Disaster recovery (r23) is one feature spread over four layers — the
``--standby-root`` / ``--repl-barrier-every`` flags on serve AND the
daemon/fleet parser, the ``resilience.replicate.ReplicationPlane``
constructor they feed, the ``sntc_repl_*`` metric family that journals
RPO/RTO and the loss-accounting law, and the resilience documentation —
and they must stay in lockstep:

1. **CLI**: each flag exists on BOTH serve and the shared
   daemon/fleet parser;
2. **CLI → ReplicationPlane**: every flag-exposed knob is a real
   ``ReplicationPlane`` keyword (``standby_root`` maps to the
   positional replica root);
3. **metrics**: the full ``sntc_repl_*`` family is declared in
   ``obs.metrics.CATALOG`` and nothing in the catalog's family is
   unknown to this checker (``check_metric_names.py`` owns catalog ⇔
   docs ⇔ emission);
4. **docs**: ``docs/RESILIENCE.md`` carries a marker-delimited
   repl-flag table (``<!-- repl-flags:begin/end -->``) with one row
   per CLI knob naming its flag — stale/extra rows are drift.

Wired as a tier-1 test (``tests/test_replicate.py``), the same
discipline as ``check_ingress_flags.py`` / ``check_tenant_flags.py``.

Exit 0 when consistent; exit 1 with a per-item report otherwise.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DOC = "docs/RESILIENCE.md"
TABLE_BEGIN = "<!-- repl-flags:begin -->"
TABLE_END = "<!-- repl-flags:end -->"

#: CLI-exposed replication knob -> its flag (serve AND daemon/fleet)
FLAG_KNOBS = {
    "standby_root": "--standby-root",
    "barrier_every": "--repl-barrier-every",
}

#: the catalog rows the replication plane emits
REPL_METRICS = (
    "sntc_repl_ships_total",
    "sntc_repl_ship_files_total",
    "sntc_repl_ship_bytes_total",
    "sntc_repl_barriers_sealed_total",
    "sntc_repl_lag_batches",
    "sntc_repl_lag_seconds",
    "sntc_repl_lag_bytes",
    "sntc_repl_divergence_total",
    "sntc_repl_promotions_total",
    "sntc_repl_tail_loss_rows_total",
)


def _read(rel: str) -> str:
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _doc_rows() -> dict:
    """knob -> documented flag, from the marker-delimited table."""
    text = _read(DOC)
    if TABLE_BEGIN not in text or TABLE_END not in text:
        return {}
    table = text.split(TABLE_BEGIN, 1)[1].split(TABLE_END, 1)[0]
    rows = {}
    for line in table.splitlines():
        m = re.match(r"\s*\|\s*`([a-z_]+)`\s*\|\s*`(--[a-z-]+)`", line)
        if m:
            rows[m.group(1)] = m.group(2)
    return rows


def check() -> list:
    """Returns human-readable drift complaints (empty = consistent)."""
    problems = []
    sys.path.insert(0, REPO)
    import inspect

    from sntc_tpu.obs.metrics import CATALOG
    from sntc_tpu.resilience.replicate import ReplicationPlane

    app_src = _read(os.path.join("sntc_tpu", "app.py"))

    # 1. CLI surface: each flag on BOTH serve and the daemon parser
    # (serve-daemon and fleet-serve share that parser)
    for knob, flag in FLAG_KNOBS.items():
        n = app_src.count(f'"{flag}"')
        if n < 2:
            problems.append(
                f"replication knob {knob!r} needs its {flag!r} flag on "
                f"BOTH serve and the daemon/fleet CLIs (found {n} "
                "declarations in sntc_tpu/app.py)"
            )

    # 2. every CLI knob is a real ReplicationPlane parameter
    params = set(inspect.signature(ReplicationPlane).parameters)
    for knob in FLAG_KNOBS:
        if knob not in params:
            problems.append(
                f"CLI knob {knob!r} is not a ReplicationPlane parameter"
            )

    # 3. catalog, both directions
    for name in REPL_METRICS:
        if name not in CATALOG:
            problems.append(
                f"replication metric {name!r} missing from "
                "obs.metrics.CATALOG"
            )
    extra = sorted(
        n for n in CATALOG
        if n.startswith("sntc_repl_") and n not in REPL_METRICS
    )
    for name in extra:
        problems.append(
            f"catalog declares {name!r} but the checker's replication "
            "family does not list it — update both"
        )

    # 4. docs
    doc = _doc_rows()
    if not doc:
        problems.append(
            f"{DOC} is missing the marker-delimited repl-flag "
            f"table ({TABLE_BEGIN} ... {TABLE_END})"
        )
    else:
        for knob, flag in FLAG_KNOBS.items():
            if knob not in doc:
                problems.append(
                    f"knob {knob!r} missing from the {DOC} flag table"
                )
            elif doc[knob] != flag:
                problems.append(
                    f"{knob!r}: docs say flag {doc[knob]!r}, CLI has "
                    f"{flag!r}"
                )
        for knob in sorted(set(doc) - set(FLAG_KNOBS)):
            problems.append(
                f"{DOC} flag table documents unknown knob {knob!r}"
            )
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("repl-flag drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {len(FLAG_KNOBS)} replication flags + "
        f"{len(REPL_METRICS)} metrics consistent across CLI, "
        "ReplicationPlane, catalog, and docs"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
