#!/usr/bin/env python
"""Static drift check: the closed-loop SLO controller's surface across
CLI ⇔ TenantSpec SLO fields ⇔ controller knob names ⇔ metric catalog
⇔ docs.

The self-driving serve plane is one feature spread over five layers —
the ``--slo-*`` / ``--controller`` flags on serve AND serve-daemon,
the ``TenantSpec`` SLO fields the daemon reads as setpoints, the
``ServeController`` knob registry (``SERVE_KNOB_NAMES``), the
``sntc_ctl_*`` metric catalog, and the knob table in
``docs/RESILIENCE.md`` — and they must stay in lockstep:

1. **CLI → SLO fields**: every ``TenantSpec`` SLO field has its flag
   on BOTH serve and serve-daemon, plus the arming pair
   ``--controller``/``--no-controller`` on both;
2. **SLO fields → spec/controller**: ``TenantSpec`` declares every
   field in ``controller.SLO_FIELDS`` and vice versa;
3. **knobs → docs**: ``docs/RESILIENCE.md`` carries a marker-delimited
   controller-knob table (``<!-- controller-knobs:begin/end -->``)
   with one row per ``SERVE_KNOB_NAMES`` entry — stale/extra rows are
   drift;
4. **metrics → catalog**: the ``sntc_ctl_*`` series are declared in
   ``obs.metrics.CATALOG`` (``check_metric_names.py`` owns catalog ⇔
   docs; this check pins the controller set exists at all).

Wired as a tier-1 test (``tests/test_controller.py``), the same
discipline as ``check_ingest_flags.py`` / ``check_tenant_flags.py``.

Exit 0 when consistent; exit 1 with a per-item report otherwise.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DOC = "docs/RESILIENCE.md"
TABLE_BEGIN = "<!-- controller-knobs:begin -->"
TABLE_END = "<!-- controller-knobs:end -->"

#: TenantSpec SLO field -> its CLI flag (on serve AND serve-daemon)
SLO_FLAGS = {
    "slo_p99_ms": "--slo-p99-ms",
    "slo_min_rows_per_sec": "--slo-min-rows-per-sec",
    "slo_max_shed_rate": "--slo-max-shed-rate",
}
ARM_FLAGS = ("--controller", "--no-controller")

#: the catalog rows the controller emits
CTL_METRICS = (
    "sntc_ctl_windows_total",
    "sntc_ctl_decisions_total",
    "sntc_ctl_knob_value",
    "sntc_ctl_slo_compliant",
    "sntc_ctl_window_p99_seconds",
)


def _read(rel: str) -> str:
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _doc_rows() -> set:
    """Documented knob names from the marker-delimited table."""
    text = _read(DOC)
    if TABLE_BEGIN not in text or TABLE_END not in text:
        return None
    table = text.split(TABLE_BEGIN, 1)[1].split(TABLE_END, 1)[0]
    rows = set()
    for line in table.splitlines():
        m = re.match(r"\s*\|\s*`([a-z_]+)`\s*\|", line)
        if m and m.group(1) != "knob":
            rows.add(m.group(1))
    return rows


def check() -> list:
    """Returns human-readable drift complaints (empty = consistent)."""
    problems = []
    sys.path.insert(0, REPO)
    from dataclasses import fields as dc_fields

    from sntc_tpu.obs.metrics import CATALOG
    from sntc_tpu.serve.controller import SERVE_KNOB_NAMES, SLO_FIELDS
    from sntc_tpu.serve.tenancy import TenantSpec

    app_src = _read(os.path.join("sntc_tpu", "app.py"))

    # 1. CLI surface: every SLO flag + the arming pair, on BOTH CLIs
    for field, flag in SLO_FLAGS.items():
        if app_src.count(f'"{flag}"') < 2:
            problems.append(
                f"SLO field {field!r} needs its {flag!r} flag on BOTH "
                "serve and serve-daemon (found fewer than 2 "
                "declarations in sntc_tpu/app.py)"
            )
    for flag in ARM_FLAGS:
        if app_src.count(f'"{flag}"') < 2:
            problems.append(
                f"{flag!r} must exist on BOTH serve and serve-daemon "
                "CLIs (found fewer than 2 declarations)"
            )

    # 2. SLO fields: checker map ⇔ controller.SLO_FIELDS ⇔ TenantSpec
    if set(SLO_FIELDS) != set(SLO_FLAGS):
        problems.append(
            f"controller.SLO_FIELDS {sorted(SLO_FIELDS)} != the "
            f"checker's flag map {sorted(SLO_FLAGS)} — update both"
        )
    spec_fields = {f.name for f in dc_fields(TenantSpec)}
    for field in SLO_FIELDS:
        if field not in spec_fields:
            problems.append(
                f"controller.SLO_FIELDS names {field!r} but TenantSpec "
                "has no such field"
            )

    # 3. docs: the marker-delimited knob table mirrors SERVE_KNOB_NAMES
    doc = _doc_rows()
    if doc is None:
        problems.append(
            f"{DOC} is missing the marker-delimited controller-knob "
            f"table ({TABLE_BEGIN} ... {TABLE_END})"
        )
    else:
        for knob in SERVE_KNOB_NAMES:
            if knob not in doc:
                problems.append(
                    f"knob {knob!r} missing from the {DOC} "
                    "controller-knob table"
                )
        for knob in sorted(doc - set(SERVE_KNOB_NAMES)):
            problems.append(
                f"{DOC} controller-knob table documents unknown knob "
                f"{knob!r}"
            )
        for flag in list(SLO_FLAGS.values()) + ["--controller"]:
            if flag not in _read(DOC):
                problems.append(f"{flag} undocumented in {DOC}")

    # 4. catalog
    for name in CTL_METRICS:
        if name not in CATALOG:
            problems.append(
                f"controller metric {name!r} missing from "
                "obs.metrics.CATALOG"
            )
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("controller-flag drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {len(SLO_FLAGS)} SLO flags + {len(CTL_METRICS)} metrics "
        "consistent across CLI, TenantSpec, knob registry, catalog, "
        "and docs"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
