#!/usr/bin/env python
"""Static drift check: ingest-autotuner surface across CLI ⇔ knobs ⇔
metric catalog ⇔ docs.

The autotuned ingest engine is one feature spread over four layers —
``python -m sntc_tpu serve`` flags, the source graph's knob registry
(``data.pipeline.KNOB_NAMES`` resolving to live setters on
``DirStreamSource``/``StreamingQuery``), the ``sntc_ingest_*`` metric
catalog that journals its behavior, and the tuning documentation — and
they must stay in lockstep:

1. **CLI → knobs**: every knob has a cold-start flag (``--read-workers``,
   ``--prefetch-batches``, ``--pipeline-depth``) plus the arming pair
   ``--autotune``/``--no-autotune`` on serve AND serve-daemon;
2. **knobs → code**: every ``KNOB_NAMES`` entry resolves on a live
   engine — the owner exposes the attribute AND its live setter
   (``set_read_workers``/``set_prefetch_batches``; ``pipeline_depth``
   is a plain engine attribute);
3. **knobs/metrics → catalog**: the ``sntc_ingest_*`` autotune series
   are declared in ``obs.metrics.CATALOG`` (``check_metric_names.py``
   owns catalog ⇔ docs; this check pins the ingest set exists at all);
4. **knobs → docs**: ``docs/PERFORMANCE.md`` carries a marker-delimited
   ingest-knob table (``<!-- ingest-knobs:begin/end -->``) with one row
   per knob naming its flag — stale/extra rows are drift.

Wired as a tier-1 test (``tests/test_ingest_pipeline.py``), the same
discipline as ``check_perf_flags.py`` / ``check_metric_names.py``.

Exit 0 when consistent; exit 1 with a per-item report otherwise.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DOC = "docs/PERFORMANCE.md"
TABLE_BEGIN = "<!-- ingest-knobs:begin -->"
TABLE_END = "<!-- ingest-knobs:end -->"

#: knob name -> its cold-start CLI flag
KNOB_FLAGS = {
    "read_workers": "--read-workers",
    "prefetch_batches": "--prefetch-batches",
    "pipeline_depth": "--pipeline-depth",
}
ARM_FLAGS = ("--autotune", "--no-autotune")

#: the catalog rows the autotuned ingest plane emits
INGEST_METRICS = (
    "sntc_ingest_stage_seconds",
    "sntc_ingest_queue_depth",
    "sntc_ingest_autotune_decisions_total",
    "sntc_ingest_knob_value",
    "sntc_ingest_bytes_read_total",
)


def _read(rel: str) -> str:
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _doc_rows() -> dict:
    """knob -> documented flag, from the marker-delimited table."""
    text = _read(DOC)
    if TABLE_BEGIN not in text or TABLE_END not in text:
        return {}
    table = text.split(TABLE_BEGIN, 1)[1].split(TABLE_END, 1)[0]
    rows = {}
    for line in table.splitlines():
        m = re.match(r"\s*\|\s*`([a-z_]+)`\s*\|\s*`(--[a-z-]+)`", line)
        if m:
            rows[m.group(1)] = m.group(2)
    return rows


def check() -> list:
    """Returns human-readable drift complaints (empty = consistent)."""
    problems = []
    sys.path.insert(0, REPO)
    from sntc_tpu.data.pipeline import KNOB_NAMES
    from sntc_tpu.obs.metrics import CATALOG
    from sntc_tpu.serve.streaming import DirStreamSource, StreamingQuery

    app_src = _read(os.path.join("sntc_tpu", "app.py"))

    # 1. CLI surface
    for knob, flag in KNOB_FLAGS.items():
        if f'"{flag}"' not in app_src:
            problems.append(
                f"knob {knob!r} has no {flag!r} flag in sntc_tpu/app.py"
            )
    for flag in ARM_FLAGS:
        if app_src.count(f'"{flag}"') < 2:
            problems.append(
                f"{flag!r} must exist on BOTH serve and serve-daemon "
                "CLIs (found fewer than 2 declarations)"
            )

    # 2. knob registry resolves on the live owners
    if set(KNOB_NAMES) != set(KNOB_FLAGS):
        problems.append(
            f"data.pipeline.KNOB_NAMES {sorted(KNOB_NAMES)} != the "
            f"checker's flag map {sorted(KNOB_FLAGS)} — update both"
        )
    for attr, setter in (
        ("read_workers", "set_read_workers"),
        ("prefetch_batches", "set_prefetch_batches"),
    ):
        if not hasattr(DirStreamSource, setter):
            problems.append(
                f"DirStreamSource lacks the live setter {setter!r} "
                f"the autotuner needs for knob {attr!r}"
            )
    import inspect

    if "pipeline_depth" not in inspect.signature(
        StreamingQuery.__init__
    ).parameters:
        problems.append(
            "StreamingQuery.__init__ lacks the pipeline_depth kwarg"
        )

    # 3. catalog
    for name in INGEST_METRICS:
        if name not in CATALOG:
            problems.append(
                f"ingest metric {name!r} missing from "
                "obs.metrics.CATALOG"
            )

    # 4. docs
    doc = _doc_rows()
    if not doc:
        problems.append(
            f"{DOC} is missing the marker-delimited ingest-knob table "
            f"({TABLE_BEGIN} ... {TABLE_END})"
        )
    else:
        for knob, flag in KNOB_FLAGS.items():
            if knob not in doc:
                problems.append(
                    f"knob {knob!r} missing from the {DOC} knob table"
                )
            elif doc[knob] != flag:
                problems.append(
                    f"{knob!r}: docs say flag {doc[knob]!r}, CLI has "
                    f"{flag!r}"
                )
        for knob in sorted(set(doc) - set(KNOB_FLAGS)):
            problems.append(
                f"{DOC} knob table documents unknown knob {knob!r}"
            )
        if "--autotune" not in _read(DOC):
            problems.append(f"--autotune undocumented in {DOC}")
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("ingest-flag drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {len(KNOB_FLAGS)} ingest knobs + {len(INGEST_METRICS)} "
        "metrics consistent across CLI, knob registry, catalog, and "
        "docs"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
