"""Stage-by-stage profile of bench config 1 (VERDICT r2 item 6).

Times each pipeline stage's fit and transform separately (warm, after a
same-shape warmup round), so the remaining gap to the sklearn proxy has
an address: indexer? assembler? scaler fit? scaler transform? LR fit?

Usage:  python scripts/profile_config1.py [--rows 250000] [--platform cpu]
Prints one JSON line per stage plus a total.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=250_000)
    ap.add_argument("--platform", default=os.environ.get("BENCH_PLATFORM"))
    args = ap.parse_args()
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    import jax

    from sntc_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()

    import numpy as np

    from bench import SEED, LR_MAX_ITER, _dataset, _feature_stages
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.parallel.context import get_default_mesh

    mesh = get_default_mesh()
    train, _ = _dataset(args.rows, binary=True)

    def run_once(record):
        stages = _feature_stages(mesh) + [
            LogisticRegression(mesh=mesh, maxIter=LR_MAX_ITER,
                               regParam=1e-4)
        ]
        frame = train
        total0 = time.perf_counter()
        for st in stages:
            name = type(st).__name__
            t0 = time.perf_counter()
            fitted = st.fit(frame) if hasattr(st, "_fit") else st
            t_fit = time.perf_counter() - t0
            t0 = time.perf_counter()
            if not isinstance(st, LogisticRegression):
                frame = fitted.transform(frame)
            t_tr = time.perf_counter() - t0
            if record is not None:
                record.append({
                    "stage": name,
                    "fit_s": round(t_fit, 4),
                    "transform_s": round(t_tr, 4),
                })
        if record is not None:
            record.append({
                "stage": "TOTAL",
                "fit_s": round(time.perf_counter() - total0, 4),
                "platform": jax.devices()[0].platform,
                "n_rows": train.num_rows,
            })

    run_once(None)  # warmup: compile + device caches
    rec: list = []
    run_once(rec)
    for row in rec:
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
