"""Stage-by-stage profile of bench config 1 (VERDICT r2 item 6), plus
the LR-FIT decomposition VERDICT r4 item 3 asked for: shard/upload,
summarizer pass, LBFGS optimize program (with iteration counts), and the
same numbers for sklearn measured in THIS invocation (drift-proof) —
scaler fit, lbfgs fit, n_iter_.  Per-iteration costs on both sides turn
"a bit faster" into "here is the single-fit floor".

Usage:  python scripts/profile_config1.py [--rows 250000] [--platform cpu]
Prints one JSON line per stage plus a total, then the decomposition.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=250_000)
    ap.add_argument("--platform", default=os.environ.get("BENCH_PLATFORM"))
    args = ap.parse_args()
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    import jax

    from sntc_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()

    import numpy as np

    from bench import SEED, LR_MAX_ITER, _dataset, _feature_stages
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.parallel.context import get_default_mesh

    mesh = get_default_mesh()
    train, _ = _dataset(args.rows, binary=True)

    def run_once(record):
        stages = _feature_stages(mesh) + [
            LogisticRegression(mesh=mesh, maxIter=LR_MAX_ITER,
                               regParam=1e-4)
        ]
        frame = train
        total0 = time.perf_counter()
        for st in stages:
            name = type(st).__name__
            t0 = time.perf_counter()
            fitted = st.fit(frame) if hasattr(st, "_fit") else st
            t_fit = time.perf_counter() - t0
            t0 = time.perf_counter()
            if not isinstance(st, LogisticRegression):
                frame = fitted.transform(frame)
            t_tr = time.perf_counter() - t0
            if record is not None:
                record.append({
                    "stage": name,
                    "fit_s": round(t_fit, 4),
                    "transform_s": round(t_tr, 4),
                })
        if record is not None:
            record.append({
                "stage": "TOTAL",
                "fit_s": round(time.perf_counter() - total0, 4),
                "platform": jax.devices()[0].platform,
                "n_rows": train.num_rows,
            })

    run_once(None)  # warmup: compile + device caches
    rec: list = []
    run_once(rec)
    for row in rec:
        print(json.dumps(row), flush=True)

    # ---- LR-fit decomposition (VERDICT r4 item 3) ----------------------
    # Re-derive the feature frame once, then time the fit's internals:
    # extract, shard/upload, summarizer treeAggregate, LBFGS program.
    import jax.numpy as jnp

    from sntc_tpu.models.logistic_regression import (
        _lr_optimize,
        _lr_summarize,
    )
    from sntc_tpu.parallel.collectives import shard_batch, shard_weights

    stages = _feature_stages(mesh)
    frame = train
    for st in stages:
        frame = (st.fit(frame) if hasattr(st, "_fit") else st).transform(frame)

    lr = LogisticRegression(mesh=mesh, maxIter=LR_MAX_ITER, regParam=1e-4)

    def timed(fn, reps=1):
        """(result, best_s): warm best-of-reps after one untimed call."""
        fn()
        best = float("inf")
        out = None
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn()
            best = min(best, time.perf_counter() - t0)
        return out, best

    X, y, w = lr._extract(frame)
    binomial, k = lr._resolve_family(y, len(y))
    y32 = y.astype(np.int32)  # hoisted: keeps identity-memoization valid

    def do_shard():
        xs, ys, _ = shard_batch(mesh, X, y32)
        jax.block_until_ready((xs, ys))
        return xs, ys

    (xs, ys), t_shard = timed(do_shard)
    ws = shard_weights(mesh, w, xs.shape[0])
    jax.block_until_ready(ws)
    # shard_batch memoizes by array identity, so the timed repeat above
    # measures the cache hit; time the true upload once with fresh copies
    Xc, yc = X.copy(), y32.copy()
    t0 = time.perf_counter()
    jax.block_until_ready(shard_batch(mesh, Xc, yc)[0])
    t_upload = time.perf_counter() - t0

    _, t_summarize = timed(
        lambda: jax.block_until_ready(_lr_summarize(xs, ys, ws, k)), reps=3
    )

    # build the prep dict from the arrays already sharded above (calling
    # _prep_data would re-extract and re-upload everything a second time)
    std, inv_std, class_counts = lr._moments_to_stats(
        *_lr_summarize(xs, ys, ws, k)
    )
    prep = {
        "xs": xs, "ys": ys, "ws": ws, "n": len(y), "d": X.shape[1],
        "k": k, "binomial": binomial, "std": std, "inv_std": inv_std,
        "class_counts": class_counts, "frame": None, "mesh": mesh,
    }
    vec = lr._grid_vectors(prep)

    def do_opt():
        res, _state = _lr_optimize(
            xs, ys, ws,
            jnp.asarray(prep["inv_std"], jnp.float32),
            jnp.asarray(vec["l2"], jnp.float32),
            jnp.asarray(vec["pen_l2"]),
            jnp.asarray(vec["l1_vec"]),
            jnp.asarray(vec["theta0"]),
            None,
            jnp.asarray(LR_MAX_ITER, jnp.int32),
            jnp.zeros_like(jnp.asarray(vec["theta0"])),
            jnp.zeros_like(jnp.asarray(vec["theta0"])),
            binomial=binomial, fit_intercept=True, k=k,
            max_iter=LR_MAX_ITER, tol=lr.getTol(), use_l1=False,
            resume=False, use_bounds=False,
        )
        jax.block_until_ready(res.x)
        return res

    res, t_opt = timed(do_opt, reps=3)
    ours_iters = int(res.n_iters)

    # ---- sklearn, SAME invocation (drift cancels) ----------------------
    from sklearn.linear_model import LogisticRegression as SkLR
    from sklearn.preprocessing import StandardScaler as SkScaler

    from bench import _proxy_xy

    Xp, yp, _ = _proxy_xy(train)
    (_, t_sk_scaler) = timed(lambda: SkScaler().fit(Xp))
    Xs = SkScaler().fit(Xp).transform(Xp)
    sk_clf, t_sk_fit = timed(
        lambda: SkLR(max_iter=LR_MAX_ITER, tol=1e-6).fit(Xs, yp)
    )
    sk_iters = int(np.max(sk_clf.n_iter_))

    decomp = {
        "stage": "LR_FIT_DECOMPOSITION",
        "upload_s": round(t_upload, 4),
        "shard_cached_s": round(t_shard, 4),
        "summarizer_pass_s": round(t_summarize, 4),
        "lbfgs_program_s": round(t_opt, 4),
        "lbfgs_iters": ours_iters,
        "per_iter_ms": round(1e3 * t_opt / max(ours_iters, 1), 3),
        "sk_scaler_fit_s": round(t_sk_scaler, 4),
        "sk_lbfgs_fit_s": round(t_sk_fit, 4),
        "sk_iters": sk_iters,
        "sk_per_iter_ms": round(1e3 * t_sk_fit / max(sk_iters, 1), 3),
        "platform": jax.devices()[0].platform,
        "n_rows": train.num_rows,
    }
    print(json.dumps(decomp), flush=True)


if __name__ == "__main__":
    main()
