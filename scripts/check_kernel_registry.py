#!/usr/bin/env python
"""Static drift check: Pallas kernels across code ⇔ registry ⇔ docs ⇔ tests.

The serving-kernel forge (r21) declares every hand-written Pallas
kernel in ``sntc_tpu.kernels.registry`` — name, owning module,
fit-guard, twin tolerance, fallback.  Four things must stay in
lockstep or the kernel tier silently rots:

1. **code → registry**: every module under ``sntc_tpu/`` containing a
   ``pl.pallas_call`` site must be the declared ``module`` of some
   registered kernel (an unregistered kernel has no guard, no poison
   ladder, no docs row, no drift protection);
2. **registry → code**: every registered kernel's declared module must
   exist and actually contain a ``pallas_call`` — a registry row whose
   kernel was deleted is dead capability documentation;
3. **registry ⇔ docs**: ``docs/PERFORMANCE.md`` carries a
   marker-delimited kernel-forge table; every registered kernel must
   have a row whose guard/tolerance/fallback match the registry, and
   every row must name a registered kernel;
4. **registry → tests**: every registered kernel name must appear in
   ``tests/test_kernels.py`` — the interpret-mode twin-equality matrix
   must exercise every kernel on every tier-1 run.

Wired as a tier-1 test (``tests/test_kernels.py``), the same
discipline as ``check_metric_names.py`` / ``check_fault_sites.py``.

Exit 0 when consistent; exit 1 with a per-kernel report otherwise.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DOC = "docs/PERFORMANCE.md"
TABLE_BEGIN = "<!-- kernel-forge:begin -->"
TABLE_END = "<!-- kernel-forge:end -->"
TESTS = "tests/test_kernels.py"

_CALL_RE = re.compile(r"\bpl\.pallas_call\b|\bpallas_call\(")


def _read(rel: str) -> str:
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _pallas_modules() -> set:
    """Repo-relative paths of every sntc_tpu module with a pallas_call
    site (the interpret shim in pallas libs themselves excluded by
    construction — we only walk sntc_tpu/)."""
    mods = set()
    for dirpath, _dirs, fnames in os.walk(os.path.join(REPO, "sntc_tpu")):
        if "__pycache__" in dirpath:
            continue
        for f in fnames:
            if not f.endswith(".py"):
                continue
            path = os.path.join(dirpath, f)
            with open(path) as fh:
                if _CALL_RE.search(fh.read()):
                    mods.add(os.path.relpath(path, REPO))
    return mods


def _doc_rows() -> dict:
    """name -> (guard, tolerance, fallback) from the marker table."""
    text = _read(DOC)
    if TABLE_BEGIN not in text or TABLE_END not in text:
        return {}
    table = text.split(TABLE_BEGIN, 1)[1].split(TABLE_END, 1)[0]
    rows = {}
    for line in table.splitlines():
        m = re.match(
            r"\s*\|\s*`([a-z0-9_]+)`\s*\|\s*`([a-z0-9_]+)`\s*\|"
            r"\s*([^|]+?)\s*\|\s*([^|]+?)\s*\|",
            line,
        )
        if m:
            rows[m.group(1)] = (m.group(2), m.group(3), m.group(4))
    return rows


def check() -> list:
    problems = []
    sys.path.insert(0, REPO)
    from sntc_tpu.kernels.registry import registered_kernels

    kernels = registered_kernels()
    by_module = {spec.module: name for name, spec in kernels.items()}

    code_mods = _pallas_modules()
    for mod in sorted(code_mods - set(by_module)):
        problems.append(
            f"{mod} contains a pallas_call but no registered KernelSpec "
            "declares it — register it in sntc_tpu/kernels/registry.py"
        )
    for mod in sorted(set(by_module) - code_mods):
        problems.append(
            f"registered kernel {by_module[mod]!r} declares module "
            f"{mod!r} but that module has no pallas_call (or does not "
            "exist) — dead registry row"
        )

    doc = _doc_rows()
    if not doc:
        problems.append(
            f"{DOC} is missing the marker-delimited kernel-forge table "
            f"({TABLE_BEGIN} ... {TABLE_END})"
        )
    for name, spec in sorted(kernels.items()):
        if doc and name not in doc:
            problems.append(
                f"registered kernel {name!r} missing from the {DOC} "
                "kernel-forge table"
            )
        elif doc:
            guard, tol, fb = doc[name]
            if guard != spec.guard_name:
                problems.append(
                    f"{name!r}: docs say guard {guard!r}, registry "
                    f"says {spec.guard_name!r}"
                )
            if tol != spec.tolerance:
                problems.append(
                    f"{name!r}: docs say tolerance {tol!r}, registry "
                    f"says {spec.tolerance!r}"
                )
            if fb != spec.fallback:
                problems.append(
                    f"{name!r}: docs say fallback {fb!r}, registry "
                    f"says {spec.fallback!r}"
                )
    for name in sorted(set(doc) - set(kernels)):
        problems.append(
            f"{DOC} documents kernel {name!r} but the registry does "
            "not declare it"
        )

    tests = _read(TESTS) if os.path.exists(os.path.join(REPO, TESTS)) else ""
    if not tests:
        problems.append(f"{TESTS} is missing — no interpret-mode matrix")
    for name in sorted(kernels):
        if tests and f'"{name}"' not in tests:
            problems.append(
                f"registered kernel {name!r} never named in {TESTS} — "
                "every kernel needs an interpret-mode tier-1 test"
            )
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("kernel-registry drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    from sntc_tpu.kernels.registry import registered_kernels

    print(
        f"ok: {len(registered_kernels())} kernels consistent across "
        "code, registry, docs/PERFORMANCE.md, and tests"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
