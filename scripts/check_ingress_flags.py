#!/usr/bin/env python
"""Static drift check: live-ingress surface across CLI ⇔ build_ingress
⇔ TenantSpec ⇔ metric catalog ⇔ docs.

The network front door (r20) is one feature spread over five layers —
``python -m sntc_tpu serve`` flags, the ``serve.ingress.build_ingress``
constructor they feed, the ``TenantSpec.ingress`` block serve-daemon
tenants configure, the ``sntc_ingress_*`` metric family that journals
the loss-accounting law, and the resilience documentation — and they
must stay in lockstep:

1. **CLI**: ``--listen-udp`` / ``--listen-tcp`` / ``--ingress-spool-mb``
   exist on BOTH serve and serve-daemon;
2. **CLI → build_ingress**: every flag-exposed knob is a real
   ``build_ingress`` keyword;
3. **TenantSpec → build_ingress**: every ``tenancy.INGRESS_KEYS`` entry
   is a real ``build_ingress`` keyword (the per-tenant block and the
   builder cannot drift apart);
4. **metrics**: the full ``sntc_ingress_*`` family is declared in
   ``obs.metrics.CATALOG`` (``check_metric_names.py`` owns catalog ⇔
   docs; this check pins the family exists at all);
5. **docs**: ``docs/RESILIENCE.md`` carries a marker-delimited
   ingress-flag table (``<!-- ingress-flags:begin/end -->``) with one
   row per CLI knob naming its flag — stale/extra rows are drift.

Wired as a tier-1 test (``tests/test_ingress.py``), the same
discipline as ``check_ingest_flags.py`` / ``check_tenant_flags.py``.

Exit 0 when consistent; exit 1 with a per-item report otherwise.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DOC = "docs/RESILIENCE.md"
TABLE_BEGIN = "<!-- ingress-flags:begin -->"
TABLE_END = "<!-- ingress-flags:end -->"

#: CLI-exposed ingress knob -> its flag (on serve AND serve-daemon)
FLAG_KNOBS = {
    "listen_udp": "--listen-udp",
    "listen_tcp": "--listen-tcp",
    "spool_mb": "--ingress-spool-mb",
}

#: the catalog rows the ingress plane emits
INGRESS_METRICS = (
    "sntc_ingress_datagrams_total",
    "sntc_ingress_frames_total",
    "sntc_ingress_bytes_total",
    "sntc_ingress_dropped_total",
    "sntc_ingress_sealed_files_total",
    "sntc_ingress_pruned_files_total",
    "sntc_ingress_spool_bytes",
    "sntc_ingress_ring_depth",
    "sntc_ingress_backpressure_state",
    "sntc_ingress_connections",
)


def _read(rel: str) -> str:
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _doc_rows() -> dict:
    """knob -> documented flag, from the marker-delimited table."""
    text = _read(DOC)
    if TABLE_BEGIN not in text or TABLE_END not in text:
        return {}
    table = text.split(TABLE_BEGIN, 1)[1].split(TABLE_END, 1)[0]
    rows = {}
    for line in table.splitlines():
        m = re.match(r"\s*\|\s*`([a-z_]+)`\s*\|\s*`(--[a-z-]+)`", line)
        if m:
            rows[m.group(1)] = m.group(2)
    return rows


def check() -> list:
    """Returns human-readable drift complaints (empty = consistent)."""
    problems = []
    sys.path.insert(0, REPO)
    import inspect

    from sntc_tpu.obs.metrics import CATALOG
    from sntc_tpu.serve.ingress import build_ingress
    from sntc_tpu.serve.tenancy import INGRESS_KEYS

    app_src = _read(os.path.join("sntc_tpu", "app.py"))

    # 1. CLI surface: each flag on BOTH serve and serve-daemon
    for knob, flag in FLAG_KNOBS.items():
        n = app_src.count(f'"{flag}"')
        if n < 2:
            problems.append(
                f"ingress knob {knob!r} needs its {flag!r} flag on "
                f"BOTH serve and serve-daemon CLIs (found {n} "
                "declarations in sntc_tpu/app.py)"
            )

    # 2/3. every CLI knob and every TenantSpec ingress key is a real
    # build_ingress keyword
    params = set(inspect.signature(build_ingress).parameters)
    for knob in FLAG_KNOBS:
        if knob not in params:
            problems.append(
                f"CLI knob {knob!r} is not a build_ingress kwarg"
            )
    for key in sorted(INGRESS_KEYS):
        if key not in params:
            problems.append(
                f"TenantSpec ingress key {key!r} is not a "
                "build_ingress kwarg"
            )
    for knob in FLAG_KNOBS:
        if knob not in INGRESS_KEYS:
            problems.append(
                f"CLI knob {knob!r} missing from tenancy.INGRESS_KEYS "
                "(serve-daemon tenants could not configure it)"
            )

    # 4. catalog
    for name in INGRESS_METRICS:
        if name not in CATALOG:
            problems.append(
                f"ingress metric {name!r} missing from "
                "obs.metrics.CATALOG"
            )
    extra = sorted(
        n for n in CATALOG
        if n.startswith("sntc_ingress_") and n not in INGRESS_METRICS
    )
    for name in extra:
        problems.append(
            f"catalog declares {name!r} but the checker's ingress "
            "family does not list it — update both"
        )

    # 5. docs
    doc = _doc_rows()
    if not doc:
        problems.append(
            f"{DOC} is missing the marker-delimited ingress-flag "
            f"table ({TABLE_BEGIN} ... {TABLE_END})"
        )
    else:
        for knob, flag in FLAG_KNOBS.items():
            if knob not in doc:
                problems.append(
                    f"knob {knob!r} missing from the {DOC} flag table"
                )
            elif doc[knob] != flag:
                problems.append(
                    f"{knob!r}: docs say flag {doc[knob]!r}, CLI has "
                    f"{flag!r}"
                )
        for knob in sorted(set(doc) - set(FLAG_KNOBS)):
            problems.append(
                f"{DOC} flag table documents unknown knob {knob!r}"
            )
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("ingress-flag drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {len(FLAG_KNOBS)} ingress flags + "
        f"{len(INGRESS_METRICS)} metrics consistent across CLI, "
        "build_ingress, TenantSpec, catalog, and docs"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
