#!/usr/bin/env python
"""Crash-consistency chaos matrix for the streaming engine.

Forks a real engine process over a directory of CSV micro-batches and
KILLS it (``SNTC_FAULTS=<site>:kill`` → ``os._exit``, no cleanup) at
each armed protocol boundary:

======================  ===============================================
``stream.wal``          pre-WAL: the batch was planned but no intent exists
``sink.write``          post-WAL / pre-sink: intent logged, no output
``stream.commit``       post-sink / pre-commit: output written, no commit
``flow.emit``           raw-capture engine: window state mutated in
                        memory, nothing durable (r14 flow scenarios)
``flow.evict``          raw-capture engine: mid-eviction pass
``flow.state_snapshot`` raw-capture engine: batch sunk, state snapshot
                        serialized but not yet on disk
======================  ===============================================

After each kill the engine is restarted on the same checkpoint dir and
must converge to EXACTLY the committed offsets and sink row counts of
an uninterrupted reference run — no duplicate rows, no lost rows
(exactly-once w.r.t. the offset log; the CSV sink dedupes a replayed
batch by rewriting ``batch_<id>.csv`` in place).

The drain scenario starts a supervised serving loop (slow sink so a
batch is reliably in flight), sends SIGTERM, and requires: exit code
0, a committed in-flight batch, and ``drain_marker.json`` in the
checkpoint dir.

Run it directly (``python scripts/chaos_crash_matrix.py``) for a JSON
verdict per scenario; ``tests/test_supervision.py`` drives the same
functions in tier-1.
"""

from __future__ import annotations

import argparse
import csv
import glob
import json
import os
import signal
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.abspath(__file__)

KILL_SITES = ("stream.wal", "sink.write", "stream.commit")
KILL_EXIT_CODE = 137  # mirrors sntc_tpu.resilience.KILL_EXIT_CODE

# durable-storage scenarios (r17).  The torn-WAL pair runs an
# append-WAL engine (compaction every 2 commits so a sealed
# wal_checkpoint.json is already behind the kill) and dies LITERALLY
# mid-append at an exact log-write index: half of batch 2's intent
# (or commit) line is flushed and the process ``os._exit``s inside the
# write — the power-loss shape (a SURVIVING engine rolls its own torn
# writes back, so only death-mid-write leaves this tail).  The restart
# must truncate the torn tail with a journaled ``truncate_torn_tail``
# repair record (storage_repair.jsonl) and reconverge committed state
# AND sink file CONTENTS bitwise with an uninterrupted reference.
# Call index map (depth 1, 1 file per batch, log appends only —
# compaction checkpoints publish via atomic writes, not appends):
# intent+commit per batch, so call 5 is batch 2's intent, call 6 its
# commit.
WAL_TORN_SCENARIOS = (
    ("wal_torn_intent", 4),  # after=4 -> the 5th log append tears
    ("wal_torn_commit", 5),
)
# the disk-fault drain scenario arms ENOSPC/EIO probabilistically at
# every serve-reachable durable write site AT ONCE (WAL appends +
# compaction, shed/dead-letter journals, health/drain markers, sink)
# on a supervised loop with retry + quarantine + shed armed, then
# SIGTERMs it: the engine must follow each artifact's declared policy
# — degrade or quarantine, never die — and exit 0 on drain.
DISK_FAULT_ENV = (
    "storage.wal:enospc:0.2:7,"
    "storage.journal:enospc:0.5:11,"
    "storage.dead_letter:io_error:0.5:13,"
    "storage.marker:io_error:0.3:17,"
    "sink.write:enospc:0.2:19"
)

# stateful flow-window scenarios (r14): an engine serving RAW pcap
# captures through the keyed-window operator (sntc_tpu/flow) is killed
# MID-WINDOW — flows genuinely span the micro-batch boundary at death —
# at each state-protocol boundary, then restarted on the same
# checkpoint.  Restart must converge BITWISE to the uninterrupted
# reference's commits and sink bytes: zero duplicated, zero lost
# windows.  The kill is armed programmatically (arm(after=N)) because
# these sites fire once per batch/commit and the kill must land with
# windows open, not on the first call.
FLOW_KILL_SITES = ("flow.emit", "flow.evict", "flow.state_snapshot")
FLOW_KILL_AFTER = {
    "flow.emit": 2,  # 3rd get_batch: spanning flows open in state
    "flow.evict": 1,  # 2nd eviction pass (the 1st batch evicts nothing)
    "flow.state_snapshot": 2,  # 3rd commit's snapshot publish
}

# multi-tenant scenarios (r12): three tenants on one ServeDaemon.
# The kill scenario arms ONE tenant's namespaced WAL boundary
# (SNTC_FAULTS=tenant/t1/stream.wal:kill) — the process dies mid-batch
# with three live tenants, and a restart on the same root must
# converge EVERY tenant to its own uninterrupted reference commits and
# sink rows (per-tenant WAL replay; t1's fault corrupted nobody
# else's checkpoint).  The isolation scenario arms one tenant's sink
# with a permanent io fault: that tenant's batches quarantine to its
# own dead-letter and the tenant escalates to QUARANTINED, while the
# other two tenants' sink output stays byte-for-byte the reference's
# and the daemon exits 0.
TENANT_IDS = ("t0", "t1", "t2")

# closed-loop SLO controller scenarios (r16).  The kill scenario arms
# the controller (confirm=1, ingest delegation off so the guarded
# serving knobs are the ones that move), declares an unreachable
# throughput SLO on t0 so the controller provably applies knob steps,
# and kills the process at the SECOND ``ctl.apply`` — after one
# decision reached controller.jsonl, mid-way through the next apply.
# The restart (controller armed again) must (a) converge every tenant
# to the controller-OFF reference commits and sink rows — the
# controller steers throughput knobs, never correctness — and (b)
# write a ``restart`` reconciliation record logging the journal-tail
# knob state against the fresh process's cold defaults.  The noisy
# scenario floods t1 (3x files, every 3rd poisoned) under a declared
# shed-rate SLO: the controller must degrade t1 down the journaled
# ladder (throttle first) while t0/t2's knobs stay untouched and
# their sink bytes stay identical to the controller-off reference,
# then go quiescent (no decisions for 30 consecutive windows).
CTL_KILL_SITES = ("ctl.apply",)
CTL_NOISY_FILES = 12
CTL_NOISY_POISON_EVERY = 3

# compute-plane fault-domain scenarios (r18): a fused + shape-bucketed
# engine (real LR pipeline through compile_serving, DeviceFaultDomain
# armed) is killed at each DEVICE boundary and restarted clean; restart
# must converge commits AND sink bytes BITWISE with an uninterrupted
# reference.  The ``device.dispatch`` row is the KILL-MID-FALLBACK
# scenario: the worker also arms ``fuse.compile:compile_error``
# (unlimited), so every fused signature is poisoned and the stream is
# serving through the eager host fallback when the kill lands — the
# fallback path must hold the same crash contract as the device path
# (and the fallback's sink bytes must equal the device reference's,
# which is the bitwise half of the tolerance contract).
DEVICE_KILL_SITES = (
    "device.dispatch", "predict.compile", "fuse.compile",
    "kernel.compile",
)
DEVICE_KILL_AFTER = {
    # dispatch fires once per batch: after=2 kills mid-stream on the
    # 3rd batch, with committed fallback batches already behind it
    "device.dispatch": 2,
    # the compile sites fire on FRESH shapes/signatures only: kill on
    # the first (batch 0's compile — nothing durable yet).  The worker
    # serves on the kernel tier (r21), so ``kernel.compile`` genuinely
    # fires inside the fused trace of batch 0's pad/traversal kernels.
    "predict.compile": 0,
    "fuse.compile": 0,
    "kernel.compile": 0,
}

# kill-mid-promotion points (r11): where the model-lifecycle promotion
# protocol dies.  pre_publish = before anything reached disk (the
# promotion is simply lost; the incumbent keeps serving); pre_swap =
# the candidate checkpoint + marker are published but the in-engine
# swap never ran (a restart loads and serves the candidate); post_swap
# = the predictor already swapped when the process died (restart
# converges identically to pre_swap — the swap itself holds no
# durable state beyond the publish).
PROMOTE_KILL_POINTS = ("pre_publish", "pre_swap", "post_swap")
# which model must serve the post-recovery batches per kill point
PROMOTE_EXPECT_CANDIDATE = {
    "pre_publish": False,
    "pre_swap": True,
    "post_swap": True,
}

# elastic serve fleet scenarios (r19): a coordinator child supervising
# two fleet-worker children over four tenants, killed at each fleet
# protocol boundary.  ``fleet.lease`` kills a WORKER mid-heartbeat
# (worker-crash: the coordinator expires its lease and migrates its
# tenants to the survivor — the dead-source migration path, no drain);
# ``fleet.assign`` kills the COORDINATOR mid-publish (restart adopts
# the last published epoch through recover());  ``fleet.migrate``
# kills the coordinator mid-ship during an explicit tenant migration
# (restart quarantines the torn ``.shipping`` copy and re-ships from
# the intact source).  Every scenario must end with each tenant
# serving on exactly one worker and per-tenant sink BYTES identical
# to an unkilled fleet reference — migration never loses a committed
# row.
FLEET_KILL_SITES = ("fleet.lease", "fleet.assign", "fleet.migrate")
FLEET_WORKER_IDS = ("fw0", "fw1")
FLEET_TENANT_IDS = ("ft0", "ft1", "ft2", "ft3")

# live-ingress scenarios (r20): an engine serving straight off a
# socket — a UDP listener spooling NetFlow datagrams into the ingress
# WAL (sntc_tpu/serve/ingress), replayed by NetFlowSpoolSource under a
# supervised StreamingQuery.  The kill scenarios send real loopback
# datagrams with a RESEND-UNTIL-SEALED sender (the sealed capture
# file's atomic rename is the ack): ``ingress.recv`` kills at the
# receive boundary, ``ingress.spool`` kills inside the seal — in both
# cases no sealed file appears for the in-flight payload, the parent
# restarts the worker and resends, and the run must converge to the
# uninterrupted reference's commits and sink BYTES bitwise (exactly-
# once into the spool: sent unique payloads == sealed files ==
# committed batches, zero drops journaled).  The burst scenario floods
# a deliberately tiny ring (ring=4) through a slowed spool: the shed
# ladder must engage (counted ``ring_overflow`` drops) instead of
# unbounded buffering, the daemon must stay alive through the burst
# and exit 0 on SIGTERM, and the drained stats must satisfy the
# conservation law EXACTLY: received == spooled + sum(dropped).
INGRESS_KILL_SITES = ("ingress.recv", "ingress.spool")
INGRESS_KILL_AFTER = {
    "ingress.recv": 1,   # the 2nd datagram dies at the boundary
    "ingress.spool": 1,  # the 2nd seal dies before the atomic write
}
INGRESS_BURST_DATAGRAMS = 150
STATS_NAME = "ingress_stats.json"  # mirrors serve.ingress.STATS_FILE

# warm-standby replication scenarios (r23): an engine with a
# ReplicationPlane wired as its commit listener is killed INSIDE the
# replication protocol at each ``repl.*`` boundary — ``repl.ship``
# mid-file-copy, ``repl.apply`` before the sealed manifest publish
# (files on the replica the manifest doesn't yet vouch for — the
# torn-ship shape), ``repl.barrier`` before the barrier append (the
# manifest is current but the barrier log is behind).  Each scenario
# then (a) PROMOTES the torn standby as-is: the promotion must succeed
# to the last SEALED barrier, quarantine every un-manifested stray to
# ``.corrupt/`` (never into the promoted tree), and satisfy the loss
# law committed == batches_through + tail_loss EXACTLY against the
# still-readable primary; (b) restarts the primary WITHOUT the fault
# and requires commits + sink bytes bitwise identical to an
# uninterrupted reference; (c) promotes again after convergence and
# requires zero tail loss.  Kill offsets are Nth-call (programmatic
# arm): ship fires per changed file, apply/barrier once per commit.
REPL_KILL_SITES = ("repl.ship", "repl.apply", "repl.barrier")
REPL_KILL_AFTER = {
    "repl.ship": 4,     # mid-ship on commit 1: commit 0 fully sealed
    "repl.apply": 1,    # 2nd manifest publish: batch 1 shipped, stale
    "repl.barrier": 1,  # 2nd barrier append: manifest ahead of barrier
}


# ---------------------------------------------------------------------------
# scenario inputs / state readers (parent side; no sntc_tpu import)
# ---------------------------------------------------------------------------


def write_inputs(watch_dir: str, n_files: int = 4, rows: int = 6) -> None:
    """``n_files`` tiny CSVs; with ``max_batch_offsets=1`` each file is
    one micro-batch."""
    os.makedirs(watch_dir, exist_ok=True)
    for i in range(n_files):
        with open(
            os.path.join(watch_dir, f"in_{i:03d}.csv"), "w", newline=""
        ) as f:
            w = csv.writer(f)
            w.writerow(["x"])
            for r in range(rows):
                w.writerow([i * 1000 + r])


def committed_state(ckpt_dir: str) -> dict:
    """Committed batch ids and their offset ranges from the WAL."""
    commits = {}
    for p in sorted(glob.glob(os.path.join(ckpt_dir, "commits", "*.json"))):
        with open(p) as f:
            rec = json.load(f)
        commits[int(os.path.splitext(os.path.basename(p))[0])] = (
            rec["start"], rec["end"],
        )
    return commits


def sink_rows(out_dir: str) -> dict:
    """Data-row count per batch CSV the sink published."""
    out = {}
    for p in sorted(glob.glob(os.path.join(out_dir, "batch_*.csv"))):
        with open(p) as f:
            out[os.path.basename(p)] = max(0, sum(1 for _ in f) - 1)
    return out


def run_worker(
    watch: str, out: str, ckpt: str, *, faults: str = "",
    slow_sink_s: float = 0.0, timeout: float = 120.0,
    pipelined: bool = False, wal_append: bool = False,
    torn_after: int = 0, armed: bool = False,
) -> subprocess.CompletedProcess:
    """One drain-and-exit engine pass in a child process."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS=faults)
    env.pop("SNTC_RESILIENCE_LOG", None)
    cmd = [
        sys.executable, SCRIPT, "--worker", "--watch", watch, "--out",
        out, "--ckpt", ckpt, "--slow-sink-s", str(slow_sink_s),
    ]
    if pipelined:
        cmd.append("--pipelined")
    if wal_append:
        cmd.append("--wal-append")
    if torn_after:
        cmd.extend(["--torn-after", str(torn_after)])
    if armed:
        cmd.append("--armed")
    return subprocess.run(
        cmd, env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
    )


def run_reference(workdir: str) -> dict:
    """One uninterrupted run over the standard inputs; every kill
    scenario is compared against its committed offsets and sink rows
    (the inputs are identical, so one reference serves all)."""
    d = os.path.join(workdir, "reference")
    watch = os.path.join(d, "in")
    write_inputs(watch)
    ref_out, ref_ckpt = os.path.join(d, "out"), os.path.join(d, "ckpt")
    ref = run_worker(watch, ref_out, ref_ckpt)
    if ref.returncode != 0:
        raise RuntimeError(
            f"reference run rc={ref.returncode}: {ref.stderr}"
        )
    return {"commits": committed_state(ref_ckpt), "rows": sink_rows(ref_out)}


def run_kill_scenario(
    workdir: str, site: str, reference: dict, pipelined: bool = False,
) -> dict:
    """Kill the engine at ``site``, restart, compare against the clean
    (serial) reference run.  ``pipelined=True`` runs both the killed
    pass and the restart with the overlapped/prefetching/bucketed
    engine — the crash contract must converge to the SERIAL reference's
    commits and sink rows regardless.  Returns a verdict dict with
    ``ok``."""
    name = site.replace(".", "_") + ("_pipelined" if pipelined else "")
    d = os.path.join(workdir, name)
    watch = os.path.join(d, "in")
    write_inputs(watch)

    out, ckpt = os.path.join(d, "out"), os.path.join(d, "ckpt")
    killed = run_worker(watch, out, ckpt, faults=f"{site}:kill",
                        pipelined=pipelined)
    if killed.returncode != KILL_EXIT_CODE:
        return {"site": site, "ok": False, "pipelined": pipelined,
                "error": f"kill run rc={killed.returncode} (expected "
                f"{KILL_EXIT_CODE}): {killed.stderr}"}

    # no faults: converge (same engine mode as the killed pass)
    restarted = run_worker(watch, out, ckpt, pipelined=pipelined)
    if restarted.returncode != 0:
        return {"site": site, "ok": False, "pipelined": pipelined,
                "error": f"restart rc={restarted.returncode}: "
                f"{restarted.stderr}"}

    got_commits = committed_state(ckpt)
    want_commits = reference["commits"]
    got_rows = sink_rows(out)
    want_rows = reference["rows"]
    ok = got_commits == want_commits and got_rows == want_rows
    return {
        "site": site, "ok": ok, "pipelined": pipelined,
        "commits": {str(k): v for k, v in got_commits.items()},
        "expected_commits": {str(k): v for k, v in want_commits.items()},
        "sink_rows": got_rows, "expected_sink_rows": want_rows,
    }


def run_drain_scenario(
    workdir: str, timeout: float = 120.0, pipelined: bool = False,
) -> dict:
    """SIGTERM a supervised serving loop mid-batch; require exit 0, a
    commit for the in-flight batch, and the drain marker.  With
    ``pipelined=True`` the drain must also settle the delivery thread's
    in-air batch before the marker lands."""
    d = os.path.join(workdir, "drain_pipelined" if pipelined else "drain")
    watch = os.path.join(d, "in")
    out, ckpt = os.path.join(d, "out"), os.path.join(d, "ckpt")
    write_inputs(watch, n_files=6)
    env = dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS="")
    cmd = [
        sys.executable, SCRIPT, "--worker", "--serve", "--watch",
        watch, "--out", out, "--ckpt", ckpt, "--slow-sink-s", "0.4",
        "--poll-interval", "0.05",
    ]
    if pipelined:
        cmd.append("--pipelined")
    proc = subprocess.Popen(
        cmd,
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )
    try:
        deadline = time.time() + timeout
        # wait until the engine is demonstrably mid-stream (first batch
        # out, more input pending) so SIGTERM lands with work in flight
        while time.time() < deadline and not sink_rows(out):
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        stdout, stderr = proc.communicate(timeout=timeout)
    except Exception:
        proc.kill()
        raise
    marker_path = os.path.join(ckpt, "drain_marker.json")
    marker = None
    if os.path.exists(marker_path):
        with open(marker_path) as f:
            marker = json.load(f)
    commits = committed_state(ckpt)
    rows = sink_rows(out)
    ok = (
        proc.returncode == 0
        and marker is not None
        and marker["in_flight_left"] == 0
        and len(commits) >= 1
        and len(rows) == len(commits)  # every commit has its sink batch
        and marker["last_committed"] == max(commits)
    )
    return {
        "site": "drain", "ok": ok, "rc": proc.returncode,
        "pipelined": pipelined,
        "marker": marker, "commits": {str(k): v for k, v in commits.items()},
        "sink_batches": len(rows), "stderr": stderr[-2000:],
        "stdout": stdout[-500:],
    }


def append_committed_state(ckpt: str) -> dict:
    """Committed (last batch id, end offset) recovered the append-WAL
    way: wal_checkpoint.json (if compaction ran) + the commits.log
    tail, tolerating a torn final line (parent-side mirror of the
    engine's own recovery; no sntc_tpu import)."""
    state = {"last": -1, "end": 0}
    ck = os.path.join(ckpt, "wal_checkpoint.json")
    if os.path.exists(ck):
        with open(ck) as f:
            core = json.load(f)
        state = {"last": core["last_committed"], "end": core["end"]}
    commits = {}
    path = os.path.join(ckpt, "commits.log")
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail: the engine repairs it
                commits[int(rec["batch_id"])] = rec["end"]
    if commits and max(commits) > state["last"]:
        state = {"last": max(commits), "end": commits[max(commits)]}
    return state


def _has_torn_tail(path: str) -> bool:
    if not os.path.exists(path):
        return False
    with open(path, "rb") as f:
        raw = f.read()
    lines = [l for l in raw.split(b"\n") if l.strip()]
    if not lines:
        return False
    try:
        json.loads(lines[-1].decode())
        return False
    except (ValueError, UnicodeDecodeError):
        return True


def run_wal_reference(workdir: str) -> dict:
    """Uninterrupted append-WAL run (compaction armed) over 6 files."""
    d = os.path.join(workdir, "wal_reference")
    watch = os.path.join(d, "in")
    write_inputs(watch, n_files=6)
    out, ckpt = os.path.join(d, "out"), os.path.join(d, "ckpt")
    ref = run_worker(watch, out, ckpt, wal_append=True)
    if ref.returncode != 0:
        raise RuntimeError(
            f"wal reference rc={ref.returncode}: {ref.stderr}"
        )
    return {
        "state": append_committed_state(ckpt),
        "sink": sink_contents(out),
    }


def run_wal_torn_scenario(
    workdir: str, name: str, torn_after: int, reference: dict,
) -> dict:
    """Kill-mid-append: a torn_write at storage.wal stops batch 2's
    intent/commit line partway and the worker dies (exit 137).  The
    restart must find the torn tail, journal a truncate_torn_tail
    repair record, and reconverge committed state + sink file CONTENTS
    bitwise with the uninterrupted reference."""
    d = os.path.join(workdir, name)
    watch = os.path.join(d, "in")
    write_inputs(watch, n_files=6)
    out, ckpt = os.path.join(d, "out"), os.path.join(d, "ckpt")
    killed = run_worker(
        watch, out, ckpt, wal_append=True, torn_after=torn_after,
    )
    if killed.returncode != KILL_EXIT_CODE:
        return {"site": name, "ok": False,
                "error": f"torn run rc={killed.returncode} (expected "
                f"{KILL_EXIT_CODE}): {killed.stderr}"}
    torn = (
        _has_torn_tail(os.path.join(ckpt, "offsets.log"))
        or _has_torn_tail(os.path.join(ckpt, "commits.log"))
    )
    if not torn:
        return {"site": name, "ok": False,
                "error": "no torn WAL tail on disk after the kill"}
    restarted = run_worker(watch, out, ckpt, wal_append=True)
    if restarted.returncode != 0:
        return {"site": name, "ok": False,
                "error": f"restart rc={restarted.returncode}: "
                f"{restarted.stderr}"}
    repair_path = os.path.join(ckpt, "storage_repair.jsonl")
    repairs = []
    if os.path.exists(repair_path):
        with open(repair_path) as f:
            repairs = [
                json.loads(line) for line in f if line.strip()
            ]
    repaired = any(
        r.get("action") == "truncate_torn_tail" for r in repairs
    )
    got_state = append_committed_state(ckpt)
    got_sink = sink_contents(out)
    ok = (
        repaired
        and got_state == reference["state"]
        and got_sink == reference["sink"]
    )
    return {
        "site": name, "ok": ok, "torn_tail_on_disk": torn,
        "repair_journaled": repaired,
        "state": got_state, "expected_state": reference["state"],
        "sink_files": sorted(got_sink),
        "sink_bitwise": got_sink == reference["sink"],
    }


def run_disk_fault_scenario(workdir: str, timeout: float = 120.0) -> dict:
    """ENOSPC/EIO armed probabilistically at every serve-reachable
    durable write site at once, on a supervised loop with retry +
    quarantine + shed armed; SIGTERM mid-stream.  Required: exit 0
    (every artifact followed its declared policy — degrade or
    quarantine, never die) with at least one commit landed."""
    d = os.path.join(workdir, "disk_faults")
    watch = os.path.join(d, "in")
    out, ckpt = os.path.join(d, "out"), os.path.join(d, "ckpt")
    write_inputs(watch, n_files=8)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SNTC_FAULTS=DISK_FAULT_ENV)
    env.pop("SNTC_RESILIENCE_LOG", None)
    cmd = [
        sys.executable, SCRIPT, "--worker", "--serve", "--armed",
        "--wal-append", "--watch", watch, "--out", out, "--ckpt",
        ckpt, "--poll-interval", "0.05", "--slow-sink-s", "0.0",
    ]
    proc = subprocess.Popen(
        cmd, env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )
    try:
        deadline = time.time() + timeout
        while time.time() < deadline and not sink_rows(out):
            time.sleep(0.05)
        time.sleep(0.5)  # let a few fault rounds land
        proc.send_signal(signal.SIGTERM)
        stdout, stderr = proc.communicate(timeout=timeout)
    except Exception:
        proc.kill()
        raise
    state = append_committed_state(ckpt)
    ok = proc.returncode == 0 and state["last"] >= 0
    return {
        "site": "disk_faults", "ok": ok, "rc": proc.returncode,
        "committed": state, "stderr": stderr[-2000:],
        "stdout": stdout[-500:],
    }


def sink_predictions(out_dir: str) -> dict:
    """Per-batch-CSV set of served ``prediction`` values (the evidence
    of WHICH model served the batch: the promotion scenarios' incumbent
    predicts class 0 everywhere, the candidate class 1)."""
    out = {}
    for p in sorted(glob.glob(os.path.join(out_dir, "batch_*.csv"))):
        with open(p) as f:
            rows = list(csv.DictReader(f))
        out[os.path.basename(p)] = sorted(
            {float(r["prediction"]) for r in rows}
        )
    return out


def sink_contents(out_dir: str) -> dict:
    """Per-batch-CSV raw bytes — the BITWISE convergence evidence the
    flow scenarios require (row counts alone would hide a feature
    value computed from replayed state diverging)."""
    out = {}
    for p in sorted(glob.glob(os.path.join(out_dir, "batch_*.csv"))):
        with open(p, "rb") as f:
            out[os.path.basename(p)] = f.read()
    return out


def run_device_worker(
    watch: str, out: str, ckpt: str, *, kill_site: str = "",
    kill_after: int = 0, poison_fused: bool = False,
    timeout: float = 120.0,
) -> subprocess.CompletedProcess:
    """One drain-and-exit pass of the fused/bucketed device-domain
    engine in a child process (the r18 scenarios)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS="")
    env.pop("SNTC_RESILIENCE_LOG", None)
    cmd = [
        sys.executable, SCRIPT, "--worker", "--device",
        "--watch", watch, "--out", out, "--ckpt", ckpt,
    ]
    if kill_site:
        cmd.extend(["--kill-site", kill_site,
                    "--kill-after", str(kill_after)])
    if poison_fused:
        cmd.append("--poison-fused")
    return subprocess.run(
        cmd, env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
    )


def run_device_reference(workdir: str) -> dict:
    """Uninterrupted device-domain run (device path end to end) — the
    convergence AND bitwise-tolerance baseline for every DEVICE kill
    scenario."""
    d = os.path.join(workdir, "device_reference")
    watch = os.path.join(d, "in")
    write_inputs(watch)
    out, ckpt = os.path.join(d, "out"), os.path.join(d, "ckpt")
    ref = run_device_worker(watch, out, ckpt)
    if ref.returncode != 0:
        raise RuntimeError(
            f"device reference rc={ref.returncode}: {ref.stderr}"
        )
    return {
        "commits": committed_state(ckpt),
        "contents": sink_contents(out),
    }


def run_device_kill_scenario(
    workdir: str, site: str, reference: dict,
) -> dict:
    """Kill the device-domain engine at ``site`` (mid-fallback for
    ``device.dispatch`` — every fused signature poisoned first),
    restart clean, require commits + sink BYTES identical to the
    uninterrupted device-path reference."""
    mid_fallback = site == "device.dispatch"
    d = os.path.join(workdir, "device_" + site.replace(".", "_"))
    watch = os.path.join(d, "in")
    write_inputs(watch)
    out, ckpt = os.path.join(d, "out"), os.path.join(d, "ckpt")
    killed = run_device_worker(
        watch, out, ckpt, kill_site=site,
        kill_after=DEVICE_KILL_AFTER[site],
        poison_fused=mid_fallback,
    )
    if killed.returncode != KILL_EXIT_CODE:
        return {"site": site, "ok": False, "mid_fallback": mid_fallback,
                "error": f"kill run rc={killed.returncode} (expected "
                f"{KILL_EXIT_CODE}): {killed.stderr}"}
    restarted = run_device_worker(watch, out, ckpt)
    if restarted.returncode != 0:
        return {"site": site, "ok": False, "mid_fallback": mid_fallback,
                "error": f"restart rc={restarted.returncode}: "
                f"{restarted.stderr}"}
    got_commits = committed_state(ckpt)
    got_contents = sink_contents(out)
    ok = (
        got_commits == reference["commits"]
        and got_contents == reference["contents"]
    )
    return {
        "site": site, "ok": ok, "mid_fallback": mid_fallback,
        "commits": {str(k): v for k, v in got_commits.items()},
        "expected_commits": {
            str(k): v for k, v in reference["commits"].items()
        },
        "sink_bitwise": got_contents == reference["contents"],
    }


def run_flow_worker(
    d: str, *, kill_site: str = "", timeout: float = 120.0,
) -> subprocess.CompletedProcess:
    """One drain-and-exit pass of the raw-capture flow engine over
    ``<d>/in`` in a child process (``--setup-flow-inputs`` must have
    run first)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS="")
    env.pop("SNTC_RESILIENCE_LOG", None)
    cmd = [
        sys.executable, SCRIPT, "--worker", "--flow", "--watch",
        os.path.join(d, "in"), "--out", os.path.join(d, "out"),
        "--ckpt", os.path.join(d, "ckpt"),
    ]
    if kill_site:
        cmd += ["--kill-site", kill_site, "--kill-after",
                str(FLOW_KILL_AFTER[kill_site])]
    return subprocess.run(
        cmd, env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
    )


def _setup_flow_inputs(d: str) -> None:
    """Capture files with flows SPANNING file boundaries plus a
    deterministic out-of-order tail (written by a child process — the
    parent side of the matrix never imports sntc_tpu)."""
    setup = subprocess.run(
        [
            sys.executable, SCRIPT, "--worker", "--setup-flow-inputs",
            "--watch", os.path.join(d, "in"),
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS=""),
        cwd=REPO, capture_output=True, text=True, timeout=120.0,
    )
    if setup.returncode != 0:
        raise RuntimeError(f"flow input setup failed: {setup.stderr}")


def run_flow_reference(workdir: str) -> dict:
    """One uninterrupted raw-capture flow run; every flow kill
    scenario compares commits AND sink bytes against it."""
    d = os.path.join(workdir, "flow_reference")
    _setup_flow_inputs(d)
    ref = run_flow_worker(d)
    if ref.returncode != 0:
        raise RuntimeError(
            f"flow reference rc={ref.returncode}: {ref.stderr}"
        )
    return {
        "commits": committed_state(os.path.join(d, "ckpt")),
        "sink": sink_contents(os.path.join(d, "out")),
    }


def run_flow_kill_scenario(
    workdir: str, site: str, reference: dict,
) -> dict:
    """Kill the flow engine mid-window at ``site``, restart on the
    same checkpoint (operator state restored from the last commit's
    snapshot, WAL intents replayed), and require commits and sink
    bytes BITWISE identical to the uninterrupted reference — zero
    duplicated or lost windows."""
    d = os.path.join(workdir, "flow_" + site.replace(".", "_"))
    _setup_flow_inputs(d)
    killed = run_flow_worker(d, kill_site=site)
    if killed.returncode != KILL_EXIT_CODE:
        return {"site": site, "ok": False,
                "error": f"kill run rc={killed.returncode} (expected "
                f"{KILL_EXIT_CODE}): {killed.stderr}"}
    restarted = run_flow_worker(d)
    if restarted.returncode != 0:
        return {"site": site, "ok": False,
                "error": f"restart rc={restarted.returncode}: "
                f"{restarted.stderr}"}
    got_commits = committed_state(os.path.join(d, "ckpt"))
    got_sink = sink_contents(os.path.join(d, "out"))
    bitwise = got_sink == reference["sink"]
    ok = got_commits == reference["commits"] and bitwise
    return {
        "site": site, "ok": ok, "sink_bitwise": bitwise,
        "commits": {str(k): v for k, v in got_commits.items()},
        "expected_commits": {
            str(k): v for k, v in reference["commits"].items()
        },
        "sink_batches": len(got_sink),
        "expected_sink_batches": len(reference["sink"]),
    }


def run_promote_worker(
    d: str, *, promote: bool, kill_point: str = "",
    faults: str = "", timeout: float = 120.0,
) -> subprocess.CompletedProcess:
    """One promotion-scenario engine pass (the worker loads the serving
    model from ``<d>/model``, the candidate from ``<d>/candidate``)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS=faults)
    env.pop("SNTC_RESILIENCE_LOG", None)
    cmd = [
        sys.executable, SCRIPT, "--worker", "--watch",
        os.path.join(d, "in"), "--out", os.path.join(d, "out"),
        "--ckpt", os.path.join(d, "ckpt"), "--model-dir",
        os.path.join(d, "model"), "--candidate-dir",
        os.path.join(d, "candidate"),
    ]
    if promote:
        cmd.append("--promote")
    if kill_point:
        cmd += ["--kill-point", kill_point]
    return subprocess.run(
        cmd, env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
    )


def _setup_promotion_dir(d: str) -> None:
    """Inputs + incumbent/candidate model checkpoints for one
    promotion scenario (models are built in a child process — the
    parent side of the matrix never imports sntc_tpu)."""
    write_inputs(os.path.join(d, "in"))
    setup = subprocess.run(
        [
            sys.executable, SCRIPT, "--worker", "--setup-models",
            "--model-dir", os.path.join(d, "model"),
            "--candidate-dir", os.path.join(d, "candidate"),
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS=""),
        cwd=REPO, capture_output=True, text=True, timeout=120.0,
    )
    if setup.returncode != 0:
        raise RuntimeError(f"model setup failed: {setup.stderr}")


def run_promotion_reference(workdir: str) -> dict:
    """One uninterrupted promote run: 2 batches under the incumbent,
    promotion, the rest under the candidate."""
    d = os.path.join(workdir, "promote_reference")
    _setup_promotion_dir(d)
    ref = run_promote_worker(d, promote=True)
    if ref.returncode != 0:
        raise RuntimeError(
            f"promotion reference rc={ref.returncode}: {ref.stderr}"
        )
    return {
        "commits": committed_state(os.path.join(d, "ckpt")),
        "predictions": sink_predictions(os.path.join(d, "out")),
    }


def run_promotion_kill_scenario(
    workdir: str, point: str, reference: dict,
) -> dict:
    """Kill the engine mid-promotion at ``point``, restart WITHOUT
    re-promoting, and require (a) committed offsets converge to the
    uninterrupted reference and (b) the post-recovery batches were
    served by the CORRECT model — the incumbent when the kill landed
    before the publish, the promoted candidate once the publish
    reached disk."""
    d = os.path.join(workdir, f"promote_{point}")
    _setup_promotion_dir(d)
    faults = {
        "pre_publish": "model.publish:kill",
        # model.swap fires twice per promotion: post-publish/pre-swap
        # and post-swap; the env kind kills the FIRST call, the
        # post_swap point arms the second programmatically in-worker
        "pre_swap": "model.swap:kill",
        "post_swap": "",
    }[point]
    killed = run_promote_worker(
        d, promote=True, faults=faults,
        kill_point=point if point == "post_swap" else "",
    )
    if killed.returncode != KILL_EXIT_CODE:
        return {"site": f"promote.{point}", "ok": False,
                "error": f"kill run rc={killed.returncode} (expected "
                f"{KILL_EXIT_CODE}): {killed.stderr}"}

    # restart on the same checkpoint, no faults, NO re-promotion: the
    # serving model is whatever the crashed promotion left durable
    restarted = run_promote_worker(d, promote=False)
    if restarted.returncode != 0:
        return {"site": f"promote.{point}", "ok": False,
                "error": f"restart rc={restarted.returncode}: "
                f"{restarted.stderr}"}

    got_commits = committed_state(os.path.join(d, "ckpt"))
    want_commits = reference["commits"]
    preds = sink_predictions(os.path.join(d, "out"))
    candidate_serves = PROMOTE_EXPECT_CANDIDATE[point]
    # batches 0-1 committed under the incumbent before the kill; the
    # post-recovery batches carry the recovered model's predictions
    want_preds = {
        "batch_000000.csv": [0.0], "batch_000001.csv": [0.0],
        "batch_000002.csv": [1.0] if candidate_serves else [0.0],
        "batch_000003.csv": [1.0] if candidate_serves else [0.0],
    }
    ok = got_commits == want_commits and preds == want_preds
    return {
        "site": f"promote.{point}", "ok": ok,
        "candidate_serves": candidate_serves,
        "commits": {str(k): v for k, v in got_commits.items()},
        "expected_commits": {str(k): v for k, v in want_commits.items()},
        "predictions": preds, "expected_predictions": want_preds,
    }


def run_daemon_worker(
    d: str, *, faults: str = "", timeout: float = 120.0, extra=(),
) -> subprocess.CompletedProcess:
    """One drain-and-exit ServeDaemon pass over the three tenant
    streams under ``<d>/in/<tid>`` in a child process.  ``extra``
    appends worker flags (``--controller``, ``--noisy``,
    ``--kill-site``...)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS=faults)
    env.pop("SNTC_RESILIENCE_LOG", None)
    return subprocess.run(
        [
            sys.executable, SCRIPT, "--worker", "--daemon", "--watch",
            os.path.join(d, "in"), "--out", os.path.join(d, "out"),
            "--ckpt", os.path.join(d, "ckpt"),
        ] + list(extra),
        env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
    )


def _write_daemon_inputs(d: str) -> None:
    """Per-tenant input dirs with DISTINCT row values (tenant index in
    the thousands digit block), so a cross-tenant mixup would show in
    the sink rows, not only the counts."""
    for k, tid in enumerate(TENANT_IDS):
        tdir = os.path.join(d, "in", tid)
        os.makedirs(tdir, exist_ok=True)
        for i in range(4):
            with open(
                os.path.join(tdir, f"in_{i:03d}.csv"), "w", newline=""
            ) as f:
                w = csv.writer(f)
                w.writerow(["x"])
                for r in range(6):
                    w.writerow([k * 100_000 + i * 1000 + r])


def _daemon_state(d: str) -> dict:
    """Per-tenant committed WAL ranges + sink rows."""
    return {
        tid: {
            "commits": committed_state(
                os.path.join(d, "ckpt", "tenant", tid, "ckpt")
            ),
            "rows": sink_rows(os.path.join(d, "out", tid)),
        }
        for tid in TENANT_IDS
    }


def run_multi_tenant_reference(workdir: str) -> dict:
    """One uninterrupted 3-tenant daemon pass; every multi-tenant
    scenario compares per-tenant against it."""
    d = os.path.join(workdir, "mt_reference")
    _write_daemon_inputs(d)
    ref = run_daemon_worker(d)
    if ref.returncode != 0:
        raise RuntimeError(
            f"multi-tenant reference rc={ref.returncode}: {ref.stderr}"
        )
    return _daemon_state(d)


def run_multi_tenant_kill_scenario(workdir: str, reference: dict) -> dict:
    """Kill the daemon at ONE tenant's namespaced WAL boundary with
    three tenants live; restart and require every tenant to converge
    to its own reference commits + sink rows."""
    d = os.path.join(workdir, "mt_kill")
    _write_daemon_inputs(d)
    killed = run_daemon_worker(d, faults="tenant/t1/stream.wal:kill")
    if killed.returncode != KILL_EXIT_CODE:
        return {"site": "tenant/t1/stream.wal", "ok": False,
                "error": f"kill run rc={killed.returncode} (expected "
                f"{KILL_EXIT_CODE}): {killed.stderr}"}
    restarted = run_daemon_worker(d)
    if restarted.returncode != 0:
        return {"site": "tenant/t1/stream.wal", "ok": False,
                "error": f"restart rc={restarted.returncode}: "
                f"{restarted.stderr}"}
    got = _daemon_state(d)
    ok = got == reference
    return {
        "site": "tenant/t1/stream.wal", "ok": ok,
        "state": {t: {"commits": {str(k): v for k, v in s["commits"]
                                  .items()},
                      "rows": s["rows"]} for t, s in got.items()},
        "expected": {t: {"commits": {str(k): v for k, v in s["commits"]
                                     .items()},
                         "rows": s["rows"]}
                     for t, s in reference.items()},
    }


def run_tenant_isolation_scenario(workdir: str, reference: dict) -> dict:
    """Arm ONE tenant's namespaced sink with a permanent io fault: its
    batches must quarantine to its OWN dead-letter (namespaced dir)
    and the tenant must escalate off the scheduler (QUARANTINED /
    STOPPED), while the other tenants' sink rows stay exactly the
    reference's and the daemon exits 0."""
    d = os.path.join(workdir, "mt_isolation")
    _write_daemon_inputs(d)
    proc = run_daemon_worker(d, faults="tenant/t1/sink.write:io:1.0:0")
    if proc.returncode != 0:
        return {"site": "tenant/t1/sink.write", "ok": False,
                "error": f"daemon rc={proc.returncode}: {proc.stderr}"}
    try:
        verdict = json.loads(
            [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")][-1]
        )
    except (IndexError, ValueError):
        return {"site": "tenant/t1/sink.write", "ok": False,
                "error": f"no JSON verdict: {proc.stdout[-500:]}"}
    got = _daemon_state(d)
    dead_letter = os.path.join(
        d, "ckpt", "tenant", "t1", "ckpt", "dead_letter",
        "dead_letter.jsonl",
    )
    clean_ok = all(
        got[tid]["rows"] == reference[tid]["rows"]
        for tid in TENANT_IDS if tid != "t1"
    )
    ok = (
        clean_ok
        and got["t1"]["rows"] == {}  # every t1 delivery failed
        and os.path.exists(dead_letter)
        and verdict["tenants"]["t1"] in ("QUARANTINED", "STOPPED")
        and all(
            verdict["tenants"][tid] == "OK"
            for tid in TENANT_IDS if tid != "t1"
        )
    )
    return {
        "site": "tenant/t1/sink.write", "ok": ok,
        "tenant_states": verdict.get("tenants"),
        "clean_sinks_match": clean_ok,
        "t1_sink_rows": got["t1"]["rows"],
        "t1_dead_letter": os.path.exists(dead_letter),
    }


def _write_ctl_noisy_inputs(d: str) -> None:
    """The controller noisy-neighbor stream: the standard 3-tenant
    inputs plus a t1 flood (3x its files), every
    ``CTL_NOISY_POISON_EVERY``-th extra file poisoned with a ragged
    line so the strict parser fails the batch (quarantine strikes —
    the flooding evidence alongside the shed burst)."""
    _write_daemon_inputs(d)
    tdir = os.path.join(d, "in", "t1")
    for i in range(4, CTL_NOISY_FILES):
        path = os.path.join(tdir, f"in_{i:03d}.csv")
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["x"])
            for r in range(6):
                w.writerow([100_000 + i * 1000 + r])
            if i % CTL_NOISY_POISON_EVERY == 0:
                f.write("garbage,not,a,row\n")


def _read_ctl_journal(d: str) -> tuple:
    """Parse ``<ckpt>/controller.jsonl``; returns (records,
    torn_line_count)."""
    path = os.path.join(d, "ckpt", "controller.jsonl")
    if not os.path.exists(path):
        return [], 0
    records, torn = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                torn += 1
    return records, torn


def run_controller_kill_scenario(workdir: str, reference: dict) -> dict:
    """Kill the controller-armed daemon mid-knob-apply (the SECOND
    ``ctl.apply`` — one decision already journaled) and restart it,
    controller still armed.  Requires: the kill landed (rc 137), the
    pre-kill journal holds >= 1 applied decision, the restart wrote a
    ``restart`` reconciliation record (journal-tail knobs vs cold
    defaults), the journal parses cleanly end to end, and every
    tenant converged to the controller-OFF reference commits + sink
    rows — the controller steers throughput knobs, never
    correctness."""
    d = os.path.join(workdir, "ctl_kill")
    _write_daemon_inputs(d)
    killed = run_daemon_worker(
        d, extra=["--controller", "--kill-site", "ctl.apply",
                  "--kill-after", "1"],
    )
    if killed.returncode != KILL_EXIT_CODE:
        return {"site": "ctl.apply", "ok": False,
                "error": f"kill run rc={killed.returncode} (expected "
                f"{KILL_EXIT_CODE}): {killed.stderr}"}
    pre_records, pre_torn = _read_ctl_journal(d)
    pre_applied = [r for r in pre_records if r.get("action") == "applied"]
    restarted = run_daemon_worker(d, extra=["--controller"])
    if restarted.returncode != 0:
        return {"site": "ctl.apply", "ok": False,
                "error": f"restart rc={restarted.returncode}: "
                f"{restarted.stderr}"}
    records, torn = _read_ctl_journal(d)
    restarts = [r for r in records if r.get("action") == "restart"]
    got = _daemon_state(d)
    ok = bool(
        got == reference
        and pre_torn == 0 and torn == 0
        and len(pre_applied) >= 1
        and len(restarts) >= 1
        and restarts[0].get("journal_knobs") is not None
        and restarts[0].get("delta")  # cold defaults != journal tail
    )
    return {
        "site": "ctl.apply", "ok": ok,
        "converged": got == reference,
        "pre_kill_applied": len(pre_applied),
        "journal_torn_lines": torn,
        "restart_records": len(restarts),
        "restart_delta": restarts[0].get("delta") if restarts else None,
    }


def run_controller_noisy_scenario(workdir: str) -> dict:
    """The noisy-neighbor arc with the controller armed, against a
    controller-OFF reference over IDENTICAL inputs.  Requires: both
    daemons exit 0; the well-behaved tenants' sink BYTES match the
    reference exactly (their knobs were never touched — also asserted
    from the journal); the violator was degraded down the journaled
    ladder starting with its quota; and the controller went quiescent
    (30 consecutive decision-free windows, reported by the worker)."""
    d_ref = os.path.join(workdir, "ctl_noisy_ref")
    d_ctl = os.path.join(workdir, "ctl_noisy")
    _write_ctl_noisy_inputs(d_ref)
    _write_ctl_noisy_inputs(d_ctl)
    ref = run_daemon_worker(d_ref, extra=["--noisy"])
    if ref.returncode != 0:
        return {"site": "controller_noisy", "ok": False,
                "error": f"reference rc={ref.returncode}: {ref.stderr}"}
    proc = run_daemon_worker(d_ctl, extra=["--noisy", "--controller"])
    if proc.returncode != 0:
        return {"site": "controller_noisy", "ok": False,
                "error": f"controller run rc={proc.returncode}: "
                f"{proc.stderr}"}
    try:
        verdict = json.loads(
            [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")][-1]
        )
    except (IndexError, ValueError):
        return {"site": "controller_noisy", "ok": False,
                "error": f"no JSON verdict: {proc.stdout[-500:]}"}
    clean_ok = all(
        sink_contents(os.path.join(d_ctl, "out", tid))
        == sink_contents(os.path.join(d_ref, "out", tid))
        for tid in TENANT_IDS if tid != "t1"
    )
    records, torn = _read_ctl_journal(d_ctl)
    applied = [r for r in records if r.get("action") == "applied"]
    t1_knobs = {r["knob"] for r in applied
                if r.get("tenant") == "t1"}
    clean_touched = [r for r in applied
                     if r.get("tenant") in ("t0", "t2")]
    ok = (
        clean_ok
        and torn == 0
        and any(k.endswith("quota") for k in t1_knobs)  # throttle rung
        and not clean_touched  # compliant neighbors never touched
        and verdict.get("ctl", {}).get("quiesced") is True
        and verdict.get("ctl", {}).get("well_behaved_compliant") is True
    )
    return {
        "site": "controller_noisy", "ok": ok,
        "clean_sinks_match": clean_ok,
        "t1_ladder_knobs": sorted(t1_knobs),
        "clean_tenant_decisions": len(clean_touched),
        "applied_total": len(applied),
        "quiesced": verdict.get("ctl", {}).get("quiesced"),
        "tenant_states": verdict.get("tenants"),
    }


# ---------------------------------------------------------------------------
# elastic-serve-fleet scenarios (r19)
# ---------------------------------------------------------------------------

FLEET_FILES_PER_TENANT = 3
FLEET_ROWS_PER_FILE = 6
FLEET_EXPECTED_ROWS = (
    len(FLEET_TENANT_IDS) * FLEET_FILES_PER_TENANT * FLEET_ROWS_PER_FILE
)


def _write_fleet_inputs(d: str) -> None:
    """Per-tenant input dirs with DISTINCT row values (tenant index in
    the hundred-thousands block) so cross-tenant mixups during a
    migration would show in the sink bytes."""
    for k, tid in enumerate(FLEET_TENANT_IDS):
        tdir = os.path.join(d, "in", tid)
        os.makedirs(tdir, exist_ok=True)
        for i in range(FLEET_FILES_PER_TENANT):
            with open(
                os.path.join(tdir, f"in_{i:03d}.csv"), "w", newline=""
            ) as f:
                w = csv.writer(f)
                w.writerow(["x"])
                for r in range(FLEET_ROWS_PER_FILE):
                    w.writerow([k * 100_000 + i * 1000 + r])


def _fleet_sink_state(d: str) -> dict:
    """Per-tenant sink-dir bytes — the sinks are SHARED absolute dirs
    outside the worker trees, so this is the per-tenant union across
    every worker that ever served the tenant."""
    return {
        tid: sink_contents(os.path.join(d, "out", tid))
        for tid in FLEET_TENANT_IDS
    }


def _fleet_rows_served(d: str) -> int:
    total = 0
    for contents in _fleet_sink_state(d).values():
        for data in contents.values():
            lines = data.decode(errors="replace").strip().splitlines()
            total += max(0, len(lines) - 1)  # minus the header
    return total


def _fleet_tenant_homes(d: str) -> dict:
    """Which workers hold an on-disk tree for each tenant — the
    single-home evidence (exactly one after any migration)."""
    homes = {}
    for tid in FLEET_TENANT_IDS:
        homes[tid] = sorted(
            os.path.basename(os.path.dirname(os.path.dirname(p)))
            for p in glob.glob(
                os.path.join(d, "root", "worker", "*", "tenant", tid)
            )
        )
    return homes


def _fleet_assignment_doc(d: str) -> dict:
    try:
        with open(
            os.path.join(d, "root", "fleet", "assignments.json")
        ) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _spawn_fleet_child(d: str, extra) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS="")
    env.pop("SNTC_RESILIENCE_LOG", None)
    return subprocess.Popen(
        [
            sys.executable, SCRIPT, "--worker",
            "--fleet-root", os.path.join(d, "root"),
            "--watch", os.path.join(d, "in"),
            "--out", os.path.join(d, "out"),
            "--tenants", ",".join(FLEET_TENANT_IDS),
            "--poll-interval", "0.05",
        ] + list(extra),
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )


def _spawn_fleet_worker(
    d: str, wid: str, *, kill_site: str = "", kill_after: int = 0,
) -> subprocess.Popen:
    extra = ["--fleet-worker", "--worker-id", wid]
    if kill_site:
        extra += ["--kill-site", kill_site,
                  "--kill-after", str(kill_after)]
    return _spawn_fleet_child(d, extra)


def _spawn_fleet_coordinator(
    d: str, *, kill_site: str = "", kill_after: int = 0,
    migrate: str = "",
) -> subprocess.Popen:
    extra = [
        "--fleet-coordinator",
        "--workers", ",".join(FLEET_WORKER_IDS),
        "--lease-ttl", "2.0", "--boot-grace", "60",
    ]
    if kill_site:
        extra += ["--kill-site", kill_site,
                  "--kill-after", str(kill_after)]
    if migrate:
        extra += ["--migrate-tenant", migrate]
    return _spawn_fleet_child(d, extra)


def _raise_fleet_drain(d: str) -> None:
    # parent-side (no sntc_tpu import): a plain atomic JSON marker
    fdir = os.path.join(d, "root", "fleet")
    os.makedirs(fdir, exist_ok=True)
    tmp = os.path.join(fdir, "fleet_drain_marker.json.tmp")
    with open(tmp, "w") as f:
        json.dump({"reason": "matrix", "ts": time.time()}, f)
    os.replace(tmp, os.path.join(fdir, "fleet_drain_marker.json"))


def _run_fleet_pass(
    d: str, *, coord_kill=("", 0), worker_kill=None, migrate: str = "",
    wait_for=None, timeout: float = 240.0,
) -> dict:
    """Drive one coordinator + two-worker fleet pass to completion:
    restart a coordinator the armed fault killed (workers killed at
    ``fleet.lease`` stay down — that IS the worker-crash scenario),
    raise the fleet drain marker once every input row reached a sink
    (and ``wait_for(d)`` holds), and return the evidence."""
    worker_kill = dict(worker_kill or {})
    _write_fleet_inputs(d)
    coord = _spawn_fleet_coordinator(
        d, kill_site=coord_kill[0], kill_after=coord_kill[1],
        migrate=migrate,
    )
    workers = {}
    for wid in FLEET_WORKER_IDS:
        site, after = worker_kill.get(wid, ("", 0))
        workers[wid] = _spawn_fleet_worker(
            d, wid, kill_site=site, kill_after=after
        )
    kills, error, status = [], None, None
    deadline = time.time() + timeout
    while time.time() < deadline:
        served = _fleet_rows_served(d)
        if served >= FLEET_EXPECTED_ROWS and (
            wait_for is None or wait_for(d, kills)
        ):
            break
        rc = coord.poll()
        if rc is not None:
            if rc == KILL_EXIT_CODE:
                kills.append(["coordinator", rc])
                # restart WITHOUT the armed kill / migrate flags: the
                # in-flight migration lives in the assignment marker
                coord = _spawn_fleet_coordinator(d)
            else:
                _o, e = coord.communicate()
                error = f"coordinator exited rc={rc} mid-pass: {e[-800:]}"
                break
        for wid, proc in workers.items():
            if proc is None:
                continue
            rc = proc.poll()
            if rc is None:
                continue
            if rc == KILL_EXIT_CODE:
                kills.append([wid, rc])
                workers[wid] = None  # stays dead: worker-crash
            else:
                _o, e = proc.communicate()
                error = f"worker {wid} exited rc={rc} mid-pass: {e[-800:]}"
                break
        if error:
            break
        time.sleep(0.2)
    else:
        error = (
            f"timed out: {_fleet_rows_served(d)}/{FLEET_EXPECTED_ROWS} "
            f"rows served, kills={kills}"
        )
    _raise_fleet_drain(d)
    procs = [coord] + [p for p in workers.values() if p is not None]
    for proc in procs:
        try:
            out, err = proc.communicate(timeout=90)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
            error = error or f"child hung past the drain marker: {err[-500:]}"
            continue
        if proc is coord:
            try:
                status = json.loads(
                    [ln for ln in out.splitlines()
                     if ln.startswith("{")][-1]
                )
            except (IndexError, ValueError):
                error = error or (
                    f"no coordinator verdict (rc={proc.returncode}): "
                    f"{err[-500:]}"
                )
        if proc.returncode not in (0, KILL_EXIT_CODE) and not error:
            error = f"child drain rc={proc.returncode}: {err[-500:]}"
    return {
        "sinks": _fleet_sink_state(d),
        "homes": _fleet_tenant_homes(d),
        "status": status,
        "kills": kills,
        "error": error,
    }


def run_fleet_reference(workdir: str) -> dict:
    """One unkilled coordinator + two-worker fleet pass — the bitwise
    baseline every fleet kill scenario compares its per-tenant sink
    union against."""
    res = _run_fleet_pass(os.path.join(workdir, "fleet_reference"))
    if res["error"]:
        raise RuntimeError(f"fleet reference failed: {res['error']}")
    return res


def run_fleet_kill_scenario(
    workdir: str, site: str, reference: dict,
) -> dict:
    """Kill the fleet at ``site`` and require convergence: the armed
    child died rc-137, every tenant ends serving from EXACTLY ONE
    worker, and the per-tenant sink union is byte-identical to the
    unkilled reference — no committed row lost, none duplicated."""
    d = os.path.join(workdir, "fleet_" + site.replace(".", "_"))

    def _killed(_d, kills):
        return bool(kills)

    if site == "fleet.lease":
        # worker-crash: fw0 dies on its SECOND heartbeat (one serve
        # round behind it, its tenants' streams unfinished) and STAYS
        # dead; the coordinator expires the lease and must migrate its
        # tenants to the survivor before the remaining rows can land
        dead = FLEET_WORKER_IDS[0]

        def _recovered(_d, kills):
            if not kills:
                return False
            tenants = _fleet_assignment_doc(_d).get("tenants", {})
            return bool(tenants) and all(
                e.get("phase") == "serving" and e.get("worker") != dead
                for e in tenants.values()
            )

        res = _run_fleet_pass(
            d, worker_kill={dead: (site, 1)}, wait_for=_recovered,
        )
        expect_killed = dead
    elif site == "fleet.assign":
        # the coordinator dies mid-publish on epoch 2 (the first
        # liveness transition) and restarts through recover()
        res = _run_fleet_pass(
            d, coord_kill=(site, 1), wait_for=_killed
        )
        expect_killed = "coordinator"
    else:  # fleet.migrate: kill-mid-ship during an explicit migration
        moved = FLEET_TENANT_IDS[0]

        def _migrated(_d, kills):
            # the kill fired AND the re-ship completed: a sealed
            # manifest exists and the tenant is back to serving
            if not kills:
                return False
            entry = _fleet_assignment_doc(_d).get("tenants", {}).get(
                moved, {}
            )
            return entry.get("phase") == "serving" and os.path.exists(
                os.path.join(
                    _d, "root", "fleet", "migrations", f"{moved}.json"
                )
            )

        res = _run_fleet_pass(
            d, coord_kill=(site, 1), migrate=moved, wait_for=_migrated
        )
        expect_killed = "coordinator"
    if res["error"]:
        return {"site": site, "ok": False, "error": res["error"],
                "kills": res["kills"], "status": res["status"]}
    killed_ok = any(
        who == expect_killed and rc == KILL_EXIT_CODE
        for who, rc in res["kills"]
    )
    single_homed = all(
        len(homes) == 1 for homes in res["homes"].values()
    )
    phases = (res["status"] or {}).get("phases", {})
    all_serving = phases.get("serving", 0) == len(FLEET_TENANT_IDS)
    bitwise = res["sinks"] == reference["sinks"]
    migrated_ok = site == "fleet.assign" or (
        ((res["status"] or {}).get("migrations") or {})
        .get("completed", 0) >= 1
    )
    ok = (killed_ok and single_homed and all_serving and bitwise
          and migrated_ok)
    return {
        "site": site, "ok": ok, "kills": res["kills"],
        "killed_expected": killed_ok,
        "tenant_homes": res["homes"],
        "single_homed": single_homed,
        "phases": phases,
        "sink_bitwise": bitwise,
        "migrations": (res["status"] or {}).get("migrations"),
    }


# ---------------------------------------------------------------------------
# live-ingress scenarios (r20)
# ---------------------------------------------------------------------------


def _setup_ingress_inputs(d: str) -> list:
    """Datagram payload files for one ingress scenario (written by a
    child process — the parent side never imports sntc_tpu).  Returns
    the payload byte strings in send order."""
    pdir = os.path.join(d, "payloads")
    setup = subprocess.run(
        [
            sys.executable, SCRIPT, "--worker", "--setup-ingress-inputs",
            "--watch", pdir,
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS=""),
        cwd=REPO, capture_output=True, text=True, timeout=120.0,
    )
    if setup.returncode != 0:
        raise RuntimeError(f"ingress input setup failed: {setup.stderr}")
    payloads = []
    for p in sorted(glob.glob(os.path.join(pdir, "payload_*.bin"))):
        with open(p, "rb") as f:
            payloads.append(f.read())
    if not payloads:
        raise RuntimeError("ingress input setup wrote no payloads")
    return payloads


def _spawn_ingress_worker(
    d: str, *, kill_site: str = "", kill_after: int = 0,
    ring: int = 4096, seal_every: int = 1, slow_spool_s: float = 0.0,
) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS="")
    env.pop("SNTC_RESILIENCE_LOG", None)
    cmd = [
        sys.executable, SCRIPT, "--worker", "--ingress",
        "--watch", os.path.join(d, "spool"),
        "--out", os.path.join(d, "out"),
        "--ckpt", os.path.join(d, "ckpt"),
        "--poll-interval", "0.05",
        "--ring", str(ring), "--seal-every", str(seal_every),
    ]
    if slow_spool_s:
        cmd += ["--slow-spool-s", str(slow_spool_s)]
    if kill_site:
        cmd += ["--kill-site", kill_site, "--kill-after", str(kill_after)]
    return subprocess.Popen(
        cmd, env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )


def _ingress_stats(d: str) -> dict:
    try:
        with open(os.path.join(d, "spool", "ingress_stats.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _wait_ingress_port(d: str, proc: subprocess.Popen,
                       timeout: float = 90.0) -> int:
    """Block until the worker publishes its ephemeral UDP port in
    ``ingress_stats.json`` (the listener's start() does this)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = _ingress_stats(d)
        if st.get("port"):
            return int(st["port"])
        if proc.poll() is not None:
            _o, e = proc.communicate()
            raise RuntimeError(
                f"ingress worker died before publishing its port "
                f"(rc={proc.returncode}): {e[-800:]}"
            )
        time.sleep(0.05)
    raise RuntimeError("ingress worker never published its port")


def _sealed_count(d: str) -> int:
    return len(glob.glob(os.path.join(d, "spool", "capture_*.nf5")))


def _drive_ingress_pass(
    d: str, payloads: list, *, kill_site: str = "", kill_after: int = 0,
    timeout: float = 180.0,
) -> dict:
    """Send each payload as one loopback datagram with seal_every=1, so
    the sealed capture file IS the ack: payload ``k`` is resent only
    after a worker death (the kill scenarios' exactly-once contract —
    a blind resend would seal a duplicate and break the bitwise
    comparison).  A worker killed by the armed fault (rc 137) is
    restarted WITHOUT the fault.  Once every payload is sealed and
    committed, SIGTERM drains the worker.  Returns the evidence."""
    proc = _spawn_ingress_worker(
        d, kill_site=kill_site, kill_after=kill_after,
    )
    kills = []
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        port = _wait_ingress_port(d, proc)
        deadline = time.time() + timeout
        k = _sealed_count(d)
        sent = 0
        pending_since = None
        while k < len(payloads):
            if time.time() > deadline:
                proc.kill()
                proc.communicate()
                return {"error": f"timed out with {k}/{len(payloads)} "
                        f"payloads sealed, kills={kills}"}
            rc = proc.poll()
            if rc is not None:
                if rc != KILL_EXIT_CODE:
                    _o, e = proc.communicate()
                    return {"error": f"worker died rc={rc} (expected "
                            f"{KILL_EXIT_CODE}): {e[-800:]}"}
                kills.append(rc)
                # the restart rebinds a fresh ephemeral port: drop the
                # stale stats marker so the port wait can't race it
                try:
                    os.unlink(os.path.join(d, "spool", STATS_NAME))
                except OSError:
                    pass
                proc = _spawn_ingress_worker(d)
                port = _wait_ingress_port(d, proc)
                pending_since = None  # resend the unsealed payload
            if pending_since is None:
                sock.sendto(payloads[k], ("127.0.0.1", port))
                sent += 1
                pending_since = time.time()
            if _sealed_count(d) > k:
                k = _sealed_count(d)
                pending_since = None
                continue
            time.sleep(0.05)
        # every payload sealed: wait for the engine to commit them all,
        # then drain via SIGTERM (listeners first, then the engine)
        while time.time() < deadline:
            if len(committed_state(os.path.join(d, "ckpt"))) >= len(payloads):
                break
            if proc.poll() is not None:
                _o, e = proc.communicate()
                return {"error": f"worker died while committing "
                        f"(rc={proc.returncode}): {e[-800:]}"}
            time.sleep(0.05)
        else:
            proc.kill()
            proc.communicate()
            return {"error": "timed out waiting for commits"}
        proc.send_signal(signal.SIGTERM)
        stdout, stderr = proc.communicate(timeout=90)
    finally:
        sock.close()
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    return {
        "rc": proc.returncode, "kills": kills, "sent": sent,
        "sealed": _sealed_count(d),
        "stats": _ingress_stats(d),
        "commits": committed_state(os.path.join(d, "ckpt")),
        "sink": sink_contents(os.path.join(d, "out")),
        "stderr": stderr[-2000:], "stdout": stdout[-500:],
        "error": None,
    }


def run_ingress_reference(workdir: str) -> dict:
    """One uninterrupted socket-fed pass over the payload set — the
    bitwise baseline for both ingress kill scenarios."""
    d = os.path.join(workdir, "ingress_reference")
    payloads = _setup_ingress_inputs(d)
    res = _drive_ingress_pass(d, payloads)
    if res["error"] or res["rc"] != 0:
        raise RuntimeError(
            f"ingress reference failed: {res.get('error')} "
            f"rc={res.get('rc')} stderr={res.get('stderr', '')[-500:]}"
        )
    return {"payloads": payloads, "commits": res["commits"],
            "sink": res["sink"]}


def run_ingress_kill_scenario(
    workdir: str, site: str, reference: dict,
) -> dict:
    """Kill the socket-fed engine at ``site`` mid-traffic, restart it,
    keep resending until sealed.  Required: the kill landed (rc 137 at
    least once), the drained final pass exits 0, sent unique payloads
    == sealed files == committed batches with ZERO journaled drops
    (sent == committed + journaled_drops, exactly), the final epoch's
    conservation law holds, and commits + sink bytes are identical to
    the uninterrupted reference."""
    d = os.path.join(workdir, "ingress_" + site.replace(".", "_"))
    payloads = _setup_ingress_inputs(d)
    res = _drive_ingress_pass(
        d, payloads, kill_site=site,
        kill_after=INGRESS_KILL_AFTER[site],
    )
    if res["error"]:
        return {"site": site, "ok": False, "error": res["error"]}
    stats = res["stats"]
    dropped = sum(stats.get("dropped", {}).values())
    law = (
        stats.get("received", -1)
        == stats.get("spooled", -2) + dropped
    )
    bitwise = res["sink"] == reference["sink"]
    ok = (
        res["rc"] == 0
        and len(res["kills"]) >= 1
        and res["sealed"] == len(payloads)
        and len(payloads) == len(res["commits"]) + dropped  # sent==committed+drops
        and law
        and stats.get("drained") is True
        and res["commits"] == reference["commits"]
        and bitwise
    )
    return {
        "site": site, "ok": ok, "rc": res["rc"],
        "kills": res["kills"], "sent": res["sent"],
        "sealed": res["sealed"], "committed": len(res["commits"]),
        "journaled_drops": dropped, "law_exact": law,
        "sink_bitwise": bitwise,
    }


def run_ingress_burst_scenario(
    workdir: str, timeout: float = 180.0,
) -> dict:
    """Flood a tiny-ring (4 datagrams) worker through a slowed spool:
    the shed ladder must engage (counted ``ring_overflow``) instead of
    unbounded buffering, the worker must stay alive through the burst
    and exit 0 on SIGTERM, and the drained stats must satisfy
    received == spooled + sum(dropped) EXACTLY."""
    d = os.path.join(workdir, "ingress_burst")
    payloads = _setup_ingress_inputs(d)
    proc = _spawn_ingress_worker(
        d, ring=4, seal_every=8, slow_spool_s=0.05,
    )
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        port = _wait_ingress_port(d, proc)
        for i in range(INGRESS_BURST_DATAGRAMS):
            sock.sendto(payloads[i % len(payloads)], ("127.0.0.1", port))
            time.sleep(0.002)
        # let the spooler work the backlog down and the engine commit a
        # few sealed files before the drain lands
        deadline = time.time() + timeout
        while time.time() < deadline:
            if committed_state(os.path.join(d, "ckpt")):
                break
            if proc.poll() is not None:
                _o, e = proc.communicate()
                return {"site": "ingress_burst", "ok": False,
                        "error": f"worker died mid-burst "
                        f"(rc={proc.returncode}): {e[-800:]}"}
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        stdout, stderr = proc.communicate(timeout=90)
    finally:
        sock.close()
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    stats = _ingress_stats(d)
    dropped = stats.get("dropped", {})
    law = (
        stats.get("received", -1)
        == stats.get("spooled", -2) + sum(dropped.values())
    )
    commits = committed_state(os.path.join(d, "ckpt"))
    ok = (
        proc.returncode == 0
        and stats.get("drained") is True
        and law
        and dropped.get("ring_overflow", 0) > 0
        and stats.get("spooled", 0) > 0
        and len(commits) >= 1
    )
    return {
        "site": "ingress_burst", "ok": ok, "rc": proc.returncode,
        "received": stats.get("received"),
        "spooled": stats.get("spooled"), "dropped": dropped,
        "law_exact": law, "commits": len(commits),
        "stderr": stderr[-2000:],
    }


def run_repl_worker(
    d: str, *, kill_site: str = "", kill_after: int = 0,
    timeout: float = 120.0,
) -> subprocess.CompletedProcess:
    """One drain-and-exit engine pass with a ReplicationPlane wired as
    the commit listener, shipping to ``<d>/standby``."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS="")
    env.pop("SNTC_RESILIENCE_LOG", None)
    cmd = [
        sys.executable, SCRIPT, "--worker", "--repl",
        "--watch", os.path.join(d, "in"),
        "--out", os.path.join(d, "out"),
        "--ckpt", os.path.join(d, "ckpt"),
        "--standby-root", os.path.join(d, "standby"),
    ]
    if kill_site:
        cmd += ["--kill-site", kill_site, "--kill-after", str(kill_after)]
    return subprocess.run(
        cmd, env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
    )


def run_promote_standby(d: str, tag: str) -> dict:
    """Promote ``<d>/standby`` into a fresh ``<d>/<tag>`` root in a
    child process; returns the promotion report."""
    res = subprocess.run(
        [
            sys.executable, SCRIPT, "--worker", "--promote-standby",
            "--standby-root", os.path.join(d, "standby"),
            "--ckpt", os.path.join(d, "ckpt"),
            "--out", os.path.join(d, "out"),
            "--dest-ckpt", os.path.join(d, tag, "ckpt"),
            "--dest-out", os.path.join(d, tag, "out"),
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS=""),
        cwd=REPO, capture_output=True, text=True, timeout=120.0,
    )
    if res.returncode != 0:
        return {"ok": False,
                "error": f"promote worker rc={res.returncode}: "
                f"{res.stderr[-800:]}"}
    return json.loads(res.stdout.strip().splitlines()[-1])


def run_repl_reference(workdir: str) -> dict:
    """One uninterrupted replicated pass: the bitwise baseline for the
    repl kill scenarios, plus its own promotion drill (the reference
    standby must promote with ZERO tail loss and the law exact)."""
    d = os.path.join(workdir, "repl_reference")
    write_inputs(os.path.join(d, "in"))
    ref = run_repl_worker(d)
    if ref.returncode != 0:
        raise RuntimeError(
            f"repl reference rc={ref.returncode}: {ref.stderr}"
        )
    promo = run_promote_standby(d, "promoted")
    if not (
        promo.get("ok") and promo.get("law_exact")
        and promo.get("tail_loss_batches") == 0
    ):
        raise RuntimeError(f"repl reference promotion failed: {promo}")
    return {
        "commits": committed_state(os.path.join(d, "ckpt")),
        "sink": sink_contents(os.path.join(d, "out")),
        "promoted_sink": sink_contents(
            os.path.join(d, "promoted", "out")
        ),
    }


def _strays_absent(promo: dict, d: str, tag: str) -> bool:
    """No quarantined (torn-ship) file may exist in the promoted tree —
    quarantine means ``.corrupt/``, never the new primary."""
    for q in promo.get("quarantined", []):
        if os.path.exists(os.path.join(d, tag, "ckpt", q["rel"])):
            return False
    return True


def run_repl_kill_scenario(
    workdir: str, site: str, reference: dict,
) -> dict:
    """Kill the replicated engine INSIDE the replication protocol at
    ``site``, then: (1) promote the torn standby as-is — either it
    promotes to the last SEALED barrier with the loss law exact and
    every torn stray quarantined out of the promoted tree, or it
    refuses and leaves NO promoted tree; (2) restart the primary
    without the fault and require commits + sink bytes bitwise equal
    to the uninterrupted reference; (3) promote again — now with zero
    tail loss and the promoted sink bitwise equal to the reference's
    own promotion."""
    d = os.path.join(workdir, "repl_" + site.replace(".", "_"))
    write_inputs(os.path.join(d, "in"))
    killed = run_repl_worker(
        d, kill_site=site, kill_after=REPL_KILL_AFTER[site],
    )
    if killed.returncode != KILL_EXIT_CODE:
        return {"site": site, "ok": False,
                "error": f"kill run rc={killed.returncode} (expected "
                f"{KILL_EXIT_CODE}): {killed.stderr[-800:]}"}

    # (1) the disaster drill: promote the torn replica before any repair
    torn = run_promote_standby(d, "promoted_torn")
    if torn.get("ok"):
        torn_ok = (
            torn.get("law_exact") is True
            and _strays_absent(torn, d, "promoted_torn")
            # repl.apply dies AFTER shipping, BEFORE the manifest
            # publish: the torn-ship strays provably exist and MUST
            # have been quarantined, not promoted
            and (site != "repl.apply"
                 or len(torn.get("quarantined", [])) >= 1)
        )
    else:
        # a refused promotion must not leave a promoted tree behind
        torn_ok = not glob.glob(
            os.path.join(d, "promoted_torn", "ckpt", "**", "*"),
            recursive=True,
        )

    # (2) restart the primary clean: bitwise convergence
    restarted = run_repl_worker(d)
    if restarted.returncode != 0:
        return {"site": site, "ok": False,
                "error": f"restart rc={restarted.returncode}: "
                f"{restarted.stderr[-800:]}"}
    got_commits = committed_state(os.path.join(d, "ckpt"))
    got_sink = sink_contents(os.path.join(d, "out"))
    bitwise = (
        got_commits == reference["commits"]
        and got_sink == reference["sink"]
    )

    # (3) converged standby: zero tail loss, promoted sink == reference's
    final = run_promote_standby(d, "promoted_final")
    final_ok = (
        final.get("ok") is True
        and final.get("law_exact") is True
        and final.get("tail_loss_batches") == 0
        and final.get("batches_through") == len(reference["commits"])
        and sink_contents(os.path.join(d, "promoted_final", "out"))
        == reference["promoted_sink"]
    )
    ok = torn_ok and bitwise and final_ok
    return {
        "site": site, "ok": ok,
        "torn_promotion": {
            "ok": torn.get("ok"), "reason": torn.get("reason"),
            "law_exact": torn.get("law_exact"),
            "tail_loss_batches": torn.get("tail_loss_batches"),
            "quarantined": len(torn.get("quarantined", [])),
            "strays_absent": torn_ok,
        },
        "primary_bitwise": bitwise,
        "final_promotion": {
            "ok": final.get("ok"), "law_exact": final.get("law_exact"),
            "tail_loss_batches": final.get("tail_loss_batches"),
            "batches_through": final.get("batches_through"),
            "rpo_seconds": final.get("rpo_seconds"),
            "rto_seconds": final.get("rto_seconds"),
        },
    }


def run_matrix(workdir: str, pipelined: bool = False) -> dict:
    """The full matrix: reference is ALWAYS the serial engine; kill and
    drain scenarios run serial or pipelined per ``pipelined`` and must
    converge to the serial reference either way."""
    reference = run_reference(workdir)
    results = [
        run_kill_scenario(workdir, s, reference, pipelined=pipelined)
        for s in KILL_SITES
    ]
    results.append(run_drain_scenario(workdir, pipelined=pipelined))
    flow_ref = run_flow_reference(workdir)
    results.extend(
        run_flow_kill_scenario(workdir, s, flow_ref)
        for s in FLOW_KILL_SITES
    )
    promo_ref = run_promotion_reference(workdir)
    results.extend(
        run_promotion_kill_scenario(workdir, p, promo_ref)
        for p in PROMOTE_KILL_POINTS
    )
    mt_ref = run_multi_tenant_reference(workdir)
    results.append(run_multi_tenant_kill_scenario(workdir, mt_ref))
    results.append(run_tenant_isolation_scenario(workdir, mt_ref))
    results.append(run_controller_kill_scenario(workdir, mt_ref))
    results.append(run_controller_noisy_scenario(workdir))
    wal_ref = run_wal_reference(workdir)
    results.extend(
        run_wal_torn_scenario(workdir, name, after, wal_ref)
        for name, after in WAL_TORN_SCENARIOS
    )
    results.append(run_disk_fault_scenario(workdir))
    dev_ref = run_device_reference(workdir)
    results.extend(
        run_device_kill_scenario(workdir, s, dev_ref)
        for s in DEVICE_KILL_SITES
    )
    fleet_ref = run_fleet_reference(workdir)
    results.extend(
        run_fleet_kill_scenario(workdir, s, fleet_ref)
        for s in FLEET_KILL_SITES
    )
    ingress_ref = run_ingress_reference(workdir)
    results.extend(
        run_ingress_kill_scenario(workdir, s, ingress_ref)
        for s in INGRESS_KILL_SITES
    )
    results.append(run_ingress_burst_scenario(workdir))
    repl_ref = run_repl_reference(workdir)
    results.extend(
        run_repl_kill_scenario(workdir, s, repl_ref)
        for s in REPL_KILL_SITES
    )
    return {"ok": all(r["ok"] for r in results), "scenarios": results}


# ---------------------------------------------------------------------------
# worker (child side)
# ---------------------------------------------------------------------------


def _const_class_pipeline(positive: bool):
    """A real servable pipeline predicting ONE class everywhere: zero
    coefficients, an intercept that pins the sigmoid — incumbent (class
    0) and candidate (class 1) outputs are trivially distinguishable in
    the sink, which is the whole point of the promotion scenarios."""
    import numpy as np

    from sntc_tpu.core.base import PipelineModel
    from sntc_tpu.feature import VectorAssembler
    from sntc_tpu.models.logistic_regression import (
        LogisticRegressionModel,
    )

    head = LogisticRegressionModel(
        coefficient_matrix=np.zeros((2, 1), np.float32),
        intercepts=np.asarray(
            [0.0, 50.0 if positive else -50.0], np.float32
        ),
        is_binomial=True,
    )
    return PipelineModel(stages=[
        VectorAssembler(inputCols=["x"], outputCol="features"),
        head,
    ])


def setup_models_main(args) -> int:
    """Write the incumbent (class-0) and candidate (class-1) serving
    checkpoints for a promotion scenario."""
    sys.path.insert(0, REPO)
    from sntc_tpu.mlio import save_model

    save_model(_const_class_pipeline(False), args.model_dir)
    save_model(_const_class_pipeline(True), args.candidate_dir)
    print(json.dumps({"model": args.model_dir,
                      "candidate": args.candidate_dir}))
    return 0


def promote_worker_main(args) -> int:
    """Promotion-scenario engine pass: serve 2 batches under the model
    loaded from ``--model-dir``, then (``--promote``) publish + swap
    the ``--candidate-dir`` checkpoint through the full ModelPromoter
    protocol — the armed kill fault fires inside it — and drain the
    rest.  Without ``--promote`` (the restart pass) the worker simply
    serves whatever checkpoint the crashed promotion left at the
    serving path."""
    sys.path.insert(0, REPO)
    from sntc_tpu.lifecycle import LifecycleManager, ModelPromoter
    from sntc_tpu.mlio import load_model
    from sntc_tpu.resilience import arm
    from sntc_tpu.serve import CsvDirSink, FileStreamSource, StreamingQuery

    model = load_model(args.model_dir)
    sink = CsvDirSink(args.out, columns=["x", "prediction"])
    src = FileStreamSource(args.watch)
    promoter = ModelPromoter(
        model, incumbent_raw=model, serving_path=args.model_dir,
        checkpoint_dir=args.ckpt, probation_batches=1,
    )
    mgr = LifecycleManager(promoter=promoter)
    q = StreamingQuery(
        model, src, sink, args.ckpt,
        max_batch_offsets=1, pipeline_depth=1, lifecycle=mgr,
    )
    if args.promote:
        q.run(max_batches=2, poll_interval=0.01)
        if args.kill_point == "post_swap":
            # the second model.swap call of THIS promotion runs right
            # after the in-engine swap — Nth-call precision the env
            # grammar has no syntax for
            arm("model.swap", kind="kill", after=1, times=1)
        promoter.load_candidate(args.candidate_dir)
        # direct promotion (the gated path is exercised in tier-1 unit
        # tests; chaos targets the publish/swap protocol itself)
        promoter.promote()
    n = q.process_available()
    print(json.dumps({"batches": n, "swapped": q.models_swapped}))
    return 0


def daemon_worker_main(args) -> int:
    """Multi-tenant engine pass: three Identity-model tenants on one
    ServeDaemon (tenant dirs ``<watch>/<tid>`` → ``<out>/<tid>``,
    checkpoints under ``<ckpt>/tenant/<tid>/``), drain-and-exit.
    Ladder thresholds are tight so the isolation scenario escalates
    within one pass; the cooldown is effectively infinite so a
    quarantined tenant stays visibly QUARANTINED in the verdict.

    ``--controller`` attaches a ServeController (confirm=1 for fast
    windows; ingest delegation OFF so the guarded serving knobs are
    the ones that move) with scenario-specific SLOs: the kill
    scenario declares an unreachable throughput floor on t0 (knob
    steps provably apply → the armed ``ctl.apply`` kill lands); the
    ``--noisy`` scenario declares a shed-rate SLO on the flooded t1
    (the degradation ladder engages).  ``--kill-site``/``--kill-after``
    arm the Nth-apply kill programmatically.  With ``--noisy
    --controller`` the worker also runs a quiescence loop (up to 600
    extra rounds) and reports whether 30 consecutive decision-free
    windows were reached before draining."""
    sys.path.insert(0, REPO)
    from sntc_tpu.core.base import Transformer
    from sntc_tpu.resilience import arm
    from sntc_tpu.serve import ServeDaemon, TenantSpec

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    if args.kill_site:
        arm(args.kill_site, kind="kill", after=args.kill_after, times=1)
    model = Identity()

    def _spec(tid, **kw):
        base = dict(
            tenant_id=tid, model=model,
            watch=os.path.join(args.watch, tid),
            out=os.path.join(args.out, tid),
            out_columns=["x"],
            max_batch_offsets=1, max_batch_failures=2,
            quarantine_after=2, stop_after=99,
            quarantine_cooldown_s=1e9,
        )
        base.update(kw)
        return TenantSpec(**base)

    if args.noisy:
        # the flooded tenant sheds (cap 2) under a declared shed-rate
        # SLO; its OWN ladder is loose so the CONTROLLER's ladder is
        # the thing being tested, not the event-strike escalation
        specs = [
            _spec("t0"),
            _spec("t1", max_pending_batches=2, shed_policy="oldest",
                  quarantine_after=10,
                  slo_max_shed_rate=0.05 if args.controller else None),
            _spec("t2"),
        ]
    elif args.controller:
        specs = [
            _spec("t0", slo_min_rows_per_sec=1e9),
            _spec("t1"),
            _spec("t2"),
        ]
    else:
        specs = [_spec(tid) for tid in TENANT_IDS]
    daemon = ServeDaemon(specs, args.ckpt)
    if args.controller:
        from sntc_tpu.resilience.control import ControlPolicy
        from sntc_tpu.serve.controller import ServeController

        daemon.controller = ServeController.for_daemon(
            daemon,
            policy=ControlPolicy(confirm=1, cooldown=0),
            ingest=False,
        )
    ctl_report = None
    try:
        n = daemon.process_available()
        if args.controller and args.noisy:
            # quiescence: keep scheduling rounds coming until the
            # controller has been silent 30 consecutive windows
            quiesced = False
            idle = 0
            for _ in range(600):
                before = daemon.controller.guard.decisions_total
                daemon.tick()
                if daemon.controller.guard.decisions_total == before:
                    idle += 1
                else:
                    idle = 0
                if idle >= 30:
                    quiesced = True
                    break
            slo = daemon.controller.slo_status()
            ctl_report = {
                "quiesced": quiesced,
                "applied": len(daemon.controller.guard.applied()),
                "escalations": daemon.controller.escalations_total,
                "well_behaved_compliant": all(
                    slo[tid]["compliant"] in (None, True)
                    for tid in ("t0", "t2")
                ),
                "knobs": daemon.controller.knob_values(),
            }
        daemon.drain()
        status = daemon.status()
    finally:
        daemon.close()
    print(json.dumps({
        "batches": n,
        "tenants": {
            tid: row["state"] for tid, row in status["tenants"].items()
        },
        "ctl": ctl_report,
    }))
    return 0


def _fleet_child_specs(args) -> dict:
    """The shared tenant catalog both fleet child roles build: one
    Identity-model file-watch tenant per id, sinks at SHARED absolute
    paths outside the worker trees (the union across workers is the
    migration-survival evidence)."""
    from sntc_tpu.core.base import Transformer
    from sntc_tpu.serve import TenantSpec

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    model = Identity()
    return {
        tid: TenantSpec(
            tenant_id=tid, model=model,
            watch=os.path.join(args.watch, tid),
            out=os.path.join(args.out, tid),
            out_columns=["x"], max_batch_offsets=1,
        )
        for tid in args.tenants.split(",")
    }


def fleet_worker_main(args) -> int:
    """One fleet worker: renew the lease, apply the published
    assignment, serve — until SIGTERM or the fleet drain marker.
    ``--kill-site fleet.lease`` arms the worker-crash kill."""
    sys.path.insert(0, REPO)
    from sntc_tpu.resilience import arm
    from sntc_tpu.serve.fleet import FleetWorker

    if args.kill_site:
        arm(args.kill_site, kind="kill", after=args.kill_after, times=1)
    worker = FleetWorker(
        args.worker_id, args.fleet_root, _fleet_child_specs(args)
    )
    status = worker.run(poll_interval=args.poll_interval)
    print(json.dumps({
        "worker": args.worker_id,
        "tenants": sorted(status.get("tenants", {})),
    }))
    return 0


def fleet_coordinator_main(args) -> int:
    """The coordinator child: tick until the fleet drain marker.
    ``--migrate-tenant`` starts one explicit migration once every
    worker is live and rows are flowing (the kill-mid-migrate
    scenario arms ``--kill-site fleet.migrate`` on top)."""
    sys.path.insert(0, REPO)
    from sntc_tpu.resilience import arm
    from sntc_tpu.serve.fleet import (
        FLEET_DRAIN_MARKER,
        FleetCoordinator,
        fleet_meta_dir,
    )

    if args.kill_site:
        arm(args.kill_site, kind="kill", after=args.kill_after, times=1)
    coord = FleetCoordinator(
        args.fleet_root, args.workers.split(","),
        _fleet_child_specs(args),
        lease_ttl_s=args.lease_ttl, boot_grace_s=args.boot_grace,
    )
    pending = args.migrate_tenant or None
    marker = os.path.join(
        fleet_meta_dir(args.fleet_root), FLEET_DRAIN_MARKER
    )
    try:
        while True:
            st = coord.tick()
            if pending is not None:
                ws = st["workers"].values()
                if all(w["state"] == "live" for w in ws) and any(
                    w["rows_done"] > 0 for w in ws
                ):
                    coord.migrate_tenant(pending, reason="rebalance")
                    pending = None
            if os.path.exists(marker):
                break
            time.sleep(args.poll_interval)
        coord.tick()
    finally:
        coord.close()
    print(json.dumps(coord.status()))
    return 0


#: sink columns the flow scenarios journal (a float-heavy subset of
#: the 78 emitted features: the bitwise comparison must cover derived
#: statistics, not just counts)
FLOW_SINK_COLS = [
    "Destination Port", "Flow Duration", "Total Fwd Packets",
    "Total Backward Packets", "Fwd Packet Length Mean",
    "Bwd Packet Length Std", "Flow IAT Mean", "Flow Bytes/s",
]


def setup_flow_inputs_main(args) -> int:
    """Write the flow scenarios' capture stream: flows spanning file
    boundaries, a deterministic out-of-order tail, and a terminal
    flush file so the reference emits every window."""
    sys.path.insert(0, REPO)
    from sntc_tpu.data.synth import write_capture_stream

    info = write_capture_stream(
        args.watch, n_files=5, flows_per_file=3, packets_per_flow=6,
        seed=11, defer_fraction=0.2, flush=True,
    )
    print(json.dumps({"files": len(info["files"]),
                      "n_flows": info["n_flows"]}))
    return 0


def flow_worker_main(args) -> int:
    """One raw-capture flow engine pass: pcap files → keyed windows →
    feature rows → CSV sink, with snapshot-at-commit state under
    ``<ckpt>/flow_state``.  ``--kill-site``/``--kill-after`` arm the
    Nth-call kill programmatically (these sites fire once per
    batch/commit; the kill must land mid-stream, which the env
    grammar's first-call semantics cannot express)."""
    sys.path.insert(0, REPO)
    from sntc_tpu.core.base import Transformer
    from sntc_tpu.flow import FlowCaptureSource
    from sntc_tpu.resilience import arm
    from sntc_tpu.serve import CsvDirSink, StreamingQuery

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    if args.kill_site:
        arm(args.kill_site, kind="kill", after=args.kill_after, times=1)
    src = FlowCaptureSource(
        args.watch, format="pcap",
        flow_timeout=0.5, activity_timeout=0.2, allowed_lateness=1.2,
        state_dir=os.path.join(args.ckpt, "flow_state"),
    )
    q = StreamingQuery(
        Identity(), src,
        CsvDirSink(args.out, columns=FLOW_SINK_COLS),
        args.ckpt, max_batch_offsets=1,
    )
    n = q.process_available()
    print(json.dumps({"batches": n,
                      "flow": src.flow_stats()}))
    return 0


def setup_ingress_inputs_main(args) -> int:
    """Write the ingress scenarios' datagram payload files: the synth
    NetFlow capture stream's file payloads (``data/synth
    .write_capture_stream(format="netflow")``), one send unit per
    ``payload_NNN.bin`` — so the parent can replay them over a real
    loopback socket without importing sntc_tpu."""
    import shutil

    sys.path.insert(0, REPO)
    from sntc_tpu.data.synth import write_capture_stream

    os.makedirs(args.watch, exist_ok=True)
    gen = os.path.join(args.watch, "_gen")
    write_capture_stream(
        gen, n_files=6, flows_per_file=3, packets_per_flow=4,
        seed=23, format="netflow", flush=False,
    )
    n = 0
    for i, path in enumerate(
        sorted(glob.glob(os.path.join(gen, "*.nf5")))
    ):
        with open(path, "rb") as f:
            data = f.read()
        with open(
            os.path.join(args.watch, f"payload_{i:03d}.bin"), "wb"
        ) as f:
            f.write(data)
        n += 1
    shutil.rmtree(gen, ignore_errors=True)
    print(json.dumps({"payloads": n}))
    return 0


#: a float-heavy NetFlow-populated subset of the 78 CICIDS2017 flow
#: features the ingress scenarios journal — bitwise sink comparison
#: must cover derived statistics, not just counts
INGRESS_SINK_COLS = [
    "Destination Port", "Flow Duration", "Total Fwd Packets",
    "Total Length of Fwd Packets", "Flow Bytes/s", "Flow Packets/s",
]


def ingress_worker_main(args) -> int:
    """One supervised socket-fed engine pass: a UDP ingress listener
    (ephemeral port, published in ``ingress_stats.json``) spooling
    into ``--watch`` with ``--seal-every`` datagrams per capture file
    and a ``--ring``-datagram ring, replayed by NetFlowSpoolSource
    under a supervised StreamingQuery until SIGTERM (listeners drain
    FIRST, then the engine — the cmd_serve ordering).
    ``--slow-spool-s`` slows every seal (the burst scenario's lever),
    ``--kill-site``/``--kill-after`` arm the Nth-call kill."""
    sys.path.insert(0, REPO)
    from sntc_tpu.core.base import Transformer
    from sntc_tpu.resilience import QuerySupervisor, arm
    from sntc_tpu.serve import CsvDirSink, StreamingQuery
    from sntc_tpu.serve.ingress import build_ingress, wire_committed_offset

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    if args.kill_site:
        arm(args.kill_site, kind="kill", after=args.kill_after, times=1)
    source, listeners = build_ingress(
        args.watch, listen_udp=0, keep_files=10_000,
        ring=args.ring, seal_every=args.seal_every,
    )
    if args.slow_spool_s > 0:
        spool = source.spool
        real_seal = spool.seal

        def slow_seal(payload, units, extra=None):
            time.sleep(args.slow_spool_s)
            return real_seal(payload, units, extra)

        spool.seal = slow_seal
    q = StreamingQuery(
        Identity(), source,
        CsvDirSink(args.out, columns=INGRESS_SINK_COLS),
        args.ckpt, max_batch_offsets=1,
    )
    wire_committed_offset(source, q.committed_end)
    for l in listeners:
        l.start()
    sup = QuerySupervisor(
        q, health_json=os.path.join(args.ckpt, "health.json"),
    )
    sup.install_signal_handlers()

    def _drain_ingress_then_engine(signum, frame):
        for l in listeners:
            try:
                l.drain()
            except Exception:
                pass
        sup.request_drain("SIGTERM")

    signal.signal(signal.SIGTERM, _drain_ingress_then_engine)
    try:
        status = sup.run(poll_interval=args.poll_interval)
    finally:
        for l in listeners:
            try:
                l.close()
            except Exception:
                pass
    print(json.dumps({
        "batches": status["engine"]["batches_done"],
        "drained": status["drained"],
        "ingress": listeners[0].stats.snapshot(),
    }))
    return 0


def _device_pipeline():
    """A servable pipeline with a REAL fused segment (the assembler
    stays eager by the single-upload rule; a DCT + const-class LR head
    fuse into one jitted program) — the fuse.compile boundary genuinely
    fires, unlike the assembler-only promotion pipeline."""
    import numpy as np

    from sntc_tpu.core.base import PipelineModel
    from sntc_tpu.feature import VectorAssembler
    from sntc_tpu.feature.dct import DCT
    from sntc_tpu.models.logistic_regression import (
        LogisticRegressionModel,
    )

    head = LogisticRegressionModel(
        coefficient_matrix=np.zeros((2, 1), np.float32),
        intercepts=np.asarray([0.0, -50.0], np.float32),
        is_binomial=True,
    )
    head.setFeaturesCol("dct")
    return PipelineModel(stages=[
        VectorAssembler(inputCols=["x"], outputCol="features"),
        DCT(inputCol="features", outputCol="dct"),
        head,
    ])


def device_worker_main(args) -> int:
    """Compute-plane scenario engine pass: the DCT+LR pipeline through
    ``compile_serving`` (one fused segment), shape buckets, and a
    DeviceFaultDomain on the predictor.  ``--poison-fused`` arms
    ``fuse.compile:compile_error`` unlimited so every fused signature
    poisons onto the eager host fallback (the kill then lands
    MID-FALLBACK); ``--kill-site``/``--kill-after`` arm the Nth-call
    kill programmatically."""
    sys.path.insert(0, REPO)
    from sntc_tpu.resilience import DeviceFaultDomain, DevicePolicy, arm
    from sntc_tpu.serve import (
        BatchPredictor,
        CsvDirSink,
        FileStreamSource,
        StreamingQuery,
        compile_serving,
    )

    # the serve plane runs the kernel tier (interpret mode on CPU) so
    # the ``kernel.compile`` boundary genuinely fires: the bucketed
    # pad rides the pad_assemble Pallas kernel every padded dispatch
    os.environ["SNTC_SERVE_KERNELS"] = "interpret"
    if args.poison_fused:
        arm("fuse.compile", kind="compile_error", times=None)
    if args.kill_site:
        arm(args.kill_site, kind="kill", after=args.kill_after, times=1)
    dom = DeviceFaultDomain(
        DevicePolicy(), probe_fn=lambda: True, probe_async=False,
    )
    pred = BatchPredictor(
        compile_serving(_device_pipeline()),
        bucket_rows=4, device_domain=dom,
    )
    q = StreamingQuery(
        pred,
        FileStreamSource(args.watch),
        CsvDirSink(args.out, columns=["x", "prediction"]),
        args.ckpt, max_batch_offsets=1,
    )
    n = q.process_available()
    print(json.dumps({"batches": n, "device": dom.stats()}))
    return 0


def worker_main(args) -> int:
    sys.path.insert(0, REPO)
    from sntc_tpu.core.base import Transformer
    from sntc_tpu.resilience import (
        QuerySupervisor,
        RetryPolicy,
        default_breakers,
    )
    from sntc_tpu.serve import CsvDirSink, FileStreamSource, StreamingQuery

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    sink = CsvDirSink(args.out, columns=["x"])
    if args.slow_sink_s > 0:
        real_add = sink.add_batch

        def slow_add(batch_id, frame):
            time.sleep(args.slow_sink_s)
            real_add(batch_id, frame)

        sink.add_batch = slow_add
    # --pipelined: the full r8 pipeline — prefetching source, shape-
    # bucketed predict (floor 4 pads the 6-row inputs to 8), overlapped
    # sink delivery — under exactly the same crash/drain contract
    src = FileStreamSource(
        args.watch, prefetch_batches=2 if args.pipelined else 0
    )
    extra = {}
    if args.wal_append:
        # torn-WAL / disk-fault scenarios: append WAL with a short
        # compaction interval so a sealed checkpoint is provably
        # involved in the recovery the scenario asserts.  Depth 1
        # keeps the storage.wal call order deterministic (intent,
        # commit, [checkpoint] per batch) so --torn-after indexes the
        # exact append the scenario documents.
        extra.update(wal_mode="append", wal_compact_every=2)
    if args.armed:
        # the disk-fault sweep serves DEGRADED, not single-shot: retry
        # per round + quarantine at the threshold (each artifact's
        # declared policy owns its own failure)
        extra.update(
            retry_policy=RetryPolicy(
                max_attempts=2, base_delay_s=0.01, jitter=0.0
            ),
            max_batch_failures=2,
        )
    q = StreamingQuery(
        Identity(), src, sink, args.ckpt,
        max_batch_offsets=1, breakers=default_breakers(),
        pipeline_depth=(
            3 if args.pipelined else (1 if args.wal_append else 2)
        ),
        overlap_sink=args.pipelined,
        shape_buckets=4 if args.pipelined else 0,
        **extra,
    )
    if args.torn_after:
        # die LITERALLY mid-append on the Nth storage.wal log write:
        # flush half the line, then os._exit — no rollback, no
        # handlers, no engine failure path.  This is a real power loss
        # shape (a surviving engine rolls its own torn writes back, so
        # only death-mid-write can leave the torn tail this scenario
        # exists to repair).
        from sntc_tpu.resilience import storage as st

        orig_append = st.append_line
        state = {"n": 0}

        def _kill_mid_append(f, text, **kw):
            if kw.get("site") == "storage.wal":
                state["n"] += 1
                if state["n"] > args.torn_after:
                    f.write(text[: max(1, len(text) // 2)])
                    f.flush()
                    os._exit(KILL_EXIT_CODE)
            return orig_append(f, text, **kw)

        st.append_line = _kill_mid_append
    if not args.serve:
        n = q.process_available()
        print(json.dumps({"batches": n}))
        return 0
    sup = QuerySupervisor(
        q, health_json=os.path.join(args.ckpt, "health.json"),
        max_pending_batches=2 if args.armed else None,
    )
    sup.install_signal_handlers()
    status = sup.run(poll_interval=args.poll_interval)
    print(json.dumps({"batches": status["engine"]["batches_done"],
                      "drained": status["drained"]}))
    return 0


def repl_worker_main(args) -> int:
    """Replication-scenario engine pass: a one-pass Identity engine
    with a ReplicationPlane wired as ``commit_listener``, shipping the
    checkpoint + sink to ``--standby-root``.  ``--kill-site`` arms the
    Nth-call kill inside the replication protocol (the engine's own
    commit is already durable when it fires — the primary must restart
    bitwise clean regardless of where replication died)."""
    sys.path.insert(0, REPO)
    from sntc_tpu.core.base import Transformer
    from sntc_tpu.resilience import arm
    from sntc_tpu.resilience.replicate import ReplicationPlane
    from sntc_tpu.serve import CsvDirSink, FileStreamSource, StreamingQuery

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    if args.kill_site:
        arm(args.kill_site, kind="kill", after=args.kill_after, times=1)
    plane = ReplicationPlane(
        args.ckpt, args.standby_root, sink_dir=args.out,
    )
    sink = CsvDirSink(args.out, columns=["x"])
    src = FileStreamSource(args.watch)
    q = StreamingQuery(
        Identity(), src, sink, args.ckpt, max_batch_offsets=1,
        commit_listener=plane.on_commit,
    )
    n = q.process_available()
    plane.close()
    print(json.dumps({"batches": n, "repl": plane.status()}))
    return 0


def promote_standby_main(args) -> int:
    """Promotion-drill pass: promote ``--standby-root``'s default
    tenant into ``--dest-ckpt``/``--dest-out``, measuring the loss law
    against the (dead but readable) primary at ``--ckpt``/``--out``.
    Prints the full promotion report; the parent judges it."""
    sys.path.insert(0, REPO)
    from sntc_tpu.resilience.replicate import promote_standby

    report = promote_standby(
        args.standby_root, "default", args.dest_ckpt,
        dest_sink=args.dest_out, primary_root=args.ckpt,
        primary_sink=args.out,
    )
    print(json.dumps(report))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--serve", action="store_true",
                    help="worker: supervised loop instead of one pass")
    ap.add_argument("--daemon", action="store_true",
                    help="worker: three-tenant ServeDaemon pass "
                    "(multi-tenant scenarios)")
    ap.add_argument("--controller", action="store_true",
                    help="worker: arm the closed-loop SLO controller "
                    "over the daemon pass (controller scenarios)")
    ap.add_argument("--noisy", action="store_true",
                    help="worker: t1 runs the flooded+poisoned noisy "
                    "stream under a declared shed-rate SLO")
    ap.add_argument("--pipelined", action="store_true",
                    help="run the engine in pipelined mode (prefetching "
                    "source + shape buckets + overlapped sink delivery); "
                    "the matrix still compares against the serial "
                    "reference")
    ap.add_argument("--watch")
    ap.add_argument("--out")
    ap.add_argument("--ckpt")
    ap.add_argument("--slow-sink-s", type=float, default=0.0)
    ap.add_argument("--poll-interval", type=float, default=0.05)
    ap.add_argument("--setup-models", action="store_true",
                    help="worker: write the promotion scenario's "
                    "incumbent/candidate checkpoints and exit")
    ap.add_argument("--flow", action="store_true",
                    help="worker: raw-capture flow-window engine pass "
                    "(stateful-operator scenarios)")
    ap.add_argument("--device", action="store_true",
                    help="worker: fused/bucketed device-fault-domain "
                    "engine pass (compute-plane scenarios)")
    ap.add_argument("--poison-fused", action="store_true",
                    help="worker: arm fuse.compile:compile_error "
                    "unlimited so every fused signature serves the "
                    "host fallback (kill-mid-fallback)")
    ap.add_argument("--setup-flow-inputs", action="store_true",
                    help="worker: write the flow scenarios' capture "
                    "stream and exit")
    ap.add_argument("--setup-ingress-inputs", action="store_true",
                    help="worker: write the ingress scenarios' "
                    "datagram payload files and exit")
    ap.add_argument("--ingress", action="store_true",
                    help="worker: supervised socket-fed engine pass "
                    "(UDP ingress listener -> spool -> "
                    "NetFlowSpoolSource; live-ingress scenarios)")
    ap.add_argument("--ring", type=int, default=4096,
                    help="ingress worker: bounded ring size in "
                    "datagrams (tiny for the burst scenario)")
    ap.add_argument("--seal-every", type=int, default=1,
                    help="ingress worker: datagrams per sealed "
                    "capture file (1 makes the sealed file the "
                    "per-datagram ack)")
    ap.add_argument("--slow-spool-s", type=float, default=0.0,
                    help="ingress worker: sleep before every seal "
                    "(forces ring overflow in the burst scenario)")
    ap.add_argument("--wal-append", action="store_true",
                    help="worker: append-WAL mode with compaction "
                    "every 2 commits (torn-WAL / disk-fault scenarios)")
    ap.add_argument("--torn-after", type=int, default=0,
                    help="worker: die mid-append (half the line "
                    "flushed, os._exit 137) on the WAL log write "
                    "after N clean ones")
    ap.add_argument("--armed", action="store_true",
                    help="worker: arm retry + poison-batch quarantine "
                    "+ backlog shedding (the disk-fault sweep serves "
                    "degraded, not single-shot)")
    ap.add_argument("--kill-site", default="",
                    help="worker: arm this site with an Nth-call kill "
                    "(--kill-after) before serving")
    ap.add_argument("--kill-after", type=int, default=0,
                    help="worker: calls to let through before the "
                    "armed --kill-site kill fires")
    ap.add_argument("--model-dir", default=None,
                    help="worker: serving-model checkpoint (doubles as "
                    "the promotion publish target)")
    ap.add_argument("--candidate-dir", default=None,
                    help="worker: candidate checkpoint to promote")
    ap.add_argument("--promote", action="store_true",
                    help="worker: run the mid-stream promotion pass")
    ap.add_argument("--kill-point", default="",
                    help="worker: post_swap arms the SECOND model.swap "
                    "call programmatically (after=1)")
    ap.add_argument("--fleet-worker", action="store_true",
                    help="worker: one elastic-fleet worker loop "
                    "(lease + assignment + serve; fleet scenarios)")
    ap.add_argument("--fleet-coordinator", action="store_true",
                    help="worker: the elastic-fleet coordinator loop "
                    "(fleet scenarios)")
    ap.add_argument("--fleet-root", default=None,
                    help="fleet child: the shared coordinator root")
    ap.add_argument("--worker-id", default="fw0",
                    help="fleet worker child: this worker's id")
    ap.add_argument("--workers", default=",".join(FLEET_WORKER_IDS),
                    help="fleet coordinator child: comma-separated "
                    "worker ids")
    ap.add_argument("--tenants", default=",".join(FLEET_TENANT_IDS),
                    help="fleet child: comma-separated tenant ids "
                    "(catalog = <watch>/<tid> -> <out>/<tid>)")
    ap.add_argument("--lease-ttl", type=float, default=2.0,
                    help="fleet coordinator child: lease TTL seconds")
    ap.add_argument("--boot-grace", type=float, default=60.0,
                    help="fleet coordinator child: first-heartbeat "
                    "grace seconds")
    ap.add_argument("--repl", action="store_true",
                    help="worker: one-pass engine with a "
                    "ReplicationPlane commit listener (warm-standby "
                    "scenarios)")
    ap.add_argument("--standby-root", default=None,
                    help="repl worker: warm-standby replica root")
    ap.add_argument("--promote-standby", action="store_true",
                    help="worker: promote the standby's default "
                    "tenant and print the report")
    ap.add_argument("--dest-ckpt", default=None,
                    help="promote-standby worker: promoted "
                    "checkpoint root")
    ap.add_argument("--dest-out", default=None,
                    help="promote-standby worker: promoted sink dir")
    ap.add_argument("--migrate-tenant", default="",
                    help="fleet coordinator child: migrate this tenant "
                    "once the fleet is live (kill-mid-migrate)")
    ap.add_argument("--workdir", default=None,
                    help="matrix scratch dir (default: a fresh tempdir)")
    args = ap.parse_args(argv)
    if args.worker:
        if args.setup_models:
            return setup_models_main(args)
        if args.setup_flow_inputs:
            return setup_flow_inputs_main(args)
        if args.setup_ingress_inputs:
            return setup_ingress_inputs_main(args)
        if args.ingress:
            return ingress_worker_main(args)
        if args.promote_standby:
            return promote_standby_main(args)
        if args.repl:
            return repl_worker_main(args)
        if args.flow:
            return flow_worker_main(args)
        if args.device:
            return device_worker_main(args)
        if args.fleet_worker:
            return fleet_worker_main(args)
        if args.fleet_coordinator:
            return fleet_coordinator_main(args)
        if args.daemon:
            return daemon_worker_main(args)
        if args.model_dir:
            return promote_worker_main(args)
        return worker_main(args)
    workdir = args.workdir
    if workdir is None:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="chaos_matrix_")
    verdict = run_matrix(workdir, pipelined=args.pipelined)
    print(json.dumps(verdict, indent=1))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
