#!/usr/bin/env python
"""Crash-consistency chaos matrix for the streaming engine.

Forks a real engine process over a directory of CSV micro-batches and
KILLS it (``SNTC_FAULTS=<site>:kill`` → ``os._exit``, no cleanup) at
each armed protocol boundary:

=================  ====================================================
``stream.wal``     pre-WAL: the batch was planned but no intent exists
``sink.write``     post-WAL / pre-sink: intent logged, no output
``stream.commit``  post-sink / pre-commit: output written, no commit
=================  ====================================================

After each kill the engine is restarted on the same checkpoint dir and
must converge to EXACTLY the committed offsets and sink row counts of
an uninterrupted reference run — no duplicate rows, no lost rows
(exactly-once w.r.t. the offset log; the CSV sink dedupes a replayed
batch by rewriting ``batch_<id>.csv`` in place).

The drain scenario starts a supervised serving loop (slow sink so a
batch is reliably in flight), sends SIGTERM, and requires: exit code
0, a committed in-flight batch, and ``drain_marker.json`` in the
checkpoint dir.

Run it directly (``python scripts/chaos_crash_matrix.py``) for a JSON
verdict per scenario; ``tests/test_supervision.py`` drives the same
functions in tier-1.
"""

from __future__ import annotations

import argparse
import csv
import glob
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.abspath(__file__)

KILL_SITES = ("stream.wal", "sink.write", "stream.commit")
KILL_EXIT_CODE = 137  # mirrors sntc_tpu.resilience.KILL_EXIT_CODE


# ---------------------------------------------------------------------------
# scenario inputs / state readers (parent side; no sntc_tpu import)
# ---------------------------------------------------------------------------


def write_inputs(watch_dir: str, n_files: int = 4, rows: int = 6) -> None:
    """``n_files`` tiny CSVs; with ``max_batch_offsets=1`` each file is
    one micro-batch."""
    os.makedirs(watch_dir, exist_ok=True)
    for i in range(n_files):
        with open(
            os.path.join(watch_dir, f"in_{i:03d}.csv"), "w", newline=""
        ) as f:
            w = csv.writer(f)
            w.writerow(["x"])
            for r in range(rows):
                w.writerow([i * 1000 + r])


def committed_state(ckpt_dir: str) -> dict:
    """Committed batch ids and their offset ranges from the WAL."""
    commits = {}
    for p in sorted(glob.glob(os.path.join(ckpt_dir, "commits", "*.json"))):
        with open(p) as f:
            rec = json.load(f)
        commits[int(os.path.splitext(os.path.basename(p))[0])] = (
            rec["start"], rec["end"],
        )
    return commits


def sink_rows(out_dir: str) -> dict:
    """Data-row count per batch CSV the sink published."""
    out = {}
    for p in sorted(glob.glob(os.path.join(out_dir, "batch_*.csv"))):
        with open(p) as f:
            out[os.path.basename(p)] = max(0, sum(1 for _ in f) - 1)
    return out


def run_worker(
    watch: str, out: str, ckpt: str, *, faults: str = "",
    slow_sink_s: float = 0.0, timeout: float = 120.0,
    pipelined: bool = False,
) -> subprocess.CompletedProcess:
    """One drain-and-exit engine pass in a child process."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS=faults)
    env.pop("SNTC_RESILIENCE_LOG", None)
    cmd = [
        sys.executable, SCRIPT, "--worker", "--watch", watch, "--out",
        out, "--ckpt", ckpt, "--slow-sink-s", str(slow_sink_s),
    ]
    if pipelined:
        cmd.append("--pipelined")
    return subprocess.run(
        cmd, env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
    )


def run_reference(workdir: str) -> dict:
    """One uninterrupted run over the standard inputs; every kill
    scenario is compared against its committed offsets and sink rows
    (the inputs are identical, so one reference serves all)."""
    d = os.path.join(workdir, "reference")
    watch = os.path.join(d, "in")
    write_inputs(watch)
    ref_out, ref_ckpt = os.path.join(d, "out"), os.path.join(d, "ckpt")
    ref = run_worker(watch, ref_out, ref_ckpt)
    if ref.returncode != 0:
        raise RuntimeError(
            f"reference run rc={ref.returncode}: {ref.stderr}"
        )
    return {"commits": committed_state(ref_ckpt), "rows": sink_rows(ref_out)}


def run_kill_scenario(
    workdir: str, site: str, reference: dict, pipelined: bool = False,
) -> dict:
    """Kill the engine at ``site``, restart, compare against the clean
    (serial) reference run.  ``pipelined=True`` runs both the killed
    pass and the restart with the overlapped/prefetching/bucketed
    engine — the crash contract must converge to the SERIAL reference's
    commits and sink rows regardless.  Returns a verdict dict with
    ``ok``."""
    name = site.replace(".", "_") + ("_pipelined" if pipelined else "")
    d = os.path.join(workdir, name)
    watch = os.path.join(d, "in")
    write_inputs(watch)

    out, ckpt = os.path.join(d, "out"), os.path.join(d, "ckpt")
    killed = run_worker(watch, out, ckpt, faults=f"{site}:kill",
                        pipelined=pipelined)
    if killed.returncode != KILL_EXIT_CODE:
        return {"site": site, "ok": False, "pipelined": pipelined,
                "error": f"kill run rc={killed.returncode} (expected "
                f"{KILL_EXIT_CODE}): {killed.stderr}"}

    # no faults: converge (same engine mode as the killed pass)
    restarted = run_worker(watch, out, ckpt, pipelined=pipelined)
    if restarted.returncode != 0:
        return {"site": site, "ok": False, "pipelined": pipelined,
                "error": f"restart rc={restarted.returncode}: "
                f"{restarted.stderr}"}

    got_commits = committed_state(ckpt)
    want_commits = reference["commits"]
    got_rows = sink_rows(out)
    want_rows = reference["rows"]
    ok = got_commits == want_commits and got_rows == want_rows
    return {
        "site": site, "ok": ok, "pipelined": pipelined,
        "commits": {str(k): v for k, v in got_commits.items()},
        "expected_commits": {str(k): v for k, v in want_commits.items()},
        "sink_rows": got_rows, "expected_sink_rows": want_rows,
    }


def run_drain_scenario(
    workdir: str, timeout: float = 120.0, pipelined: bool = False,
) -> dict:
    """SIGTERM a supervised serving loop mid-batch; require exit 0, a
    commit for the in-flight batch, and the drain marker.  With
    ``pipelined=True`` the drain must also settle the delivery thread's
    in-air batch before the marker lands."""
    d = os.path.join(workdir, "drain_pipelined" if pipelined else "drain")
    watch = os.path.join(d, "in")
    out, ckpt = os.path.join(d, "out"), os.path.join(d, "ckpt")
    write_inputs(watch, n_files=6)
    env = dict(os.environ, JAX_PLATFORMS="cpu", SNTC_FAULTS="")
    cmd = [
        sys.executable, SCRIPT, "--worker", "--serve", "--watch",
        watch, "--out", out, "--ckpt", ckpt, "--slow-sink-s", "0.4",
        "--poll-interval", "0.05",
    ]
    if pipelined:
        cmd.append("--pipelined")
    proc = subprocess.Popen(
        cmd,
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )
    try:
        deadline = time.time() + timeout
        # wait until the engine is demonstrably mid-stream (first batch
        # out, more input pending) so SIGTERM lands with work in flight
        while time.time() < deadline and not sink_rows(out):
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        stdout, stderr = proc.communicate(timeout=timeout)
    except Exception:
        proc.kill()
        raise
    marker_path = os.path.join(ckpt, "drain_marker.json")
    marker = None
    if os.path.exists(marker_path):
        with open(marker_path) as f:
            marker = json.load(f)
    commits = committed_state(ckpt)
    rows = sink_rows(out)
    ok = (
        proc.returncode == 0
        and marker is not None
        and marker["in_flight_left"] == 0
        and len(commits) >= 1
        and len(rows) == len(commits)  # every commit has its sink batch
        and marker["last_committed"] == max(commits)
    )
    return {
        "site": "drain", "ok": ok, "rc": proc.returncode,
        "pipelined": pipelined,
        "marker": marker, "commits": {str(k): v for k, v in commits.items()},
        "sink_batches": len(rows), "stderr": stderr[-2000:],
        "stdout": stdout[-500:],
    }


def run_matrix(workdir: str, pipelined: bool = False) -> dict:
    """The full matrix: reference is ALWAYS the serial engine; kill and
    drain scenarios run serial or pipelined per ``pipelined`` and must
    converge to the serial reference either way."""
    reference = run_reference(workdir)
    results = [
        run_kill_scenario(workdir, s, reference, pipelined=pipelined)
        for s in KILL_SITES
    ]
    results.append(run_drain_scenario(workdir, pipelined=pipelined))
    return {"ok": all(r["ok"] for r in results), "scenarios": results}


# ---------------------------------------------------------------------------
# worker (child side)
# ---------------------------------------------------------------------------


def worker_main(args) -> int:
    sys.path.insert(0, REPO)
    from sntc_tpu.core.base import Transformer
    from sntc_tpu.resilience import QuerySupervisor, default_breakers
    from sntc_tpu.serve import CsvDirSink, FileStreamSource, StreamingQuery

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    sink = CsvDirSink(args.out, columns=["x"])
    if args.slow_sink_s > 0:
        real_add = sink.add_batch

        def slow_add(batch_id, frame):
            time.sleep(args.slow_sink_s)
            real_add(batch_id, frame)

        sink.add_batch = slow_add
    # --pipelined: the full r8 pipeline — prefetching source, shape-
    # bucketed predict (floor 4 pads the 6-row inputs to 8), overlapped
    # sink delivery — under exactly the same crash/drain contract
    src = FileStreamSource(
        args.watch, prefetch_batches=2 if args.pipelined else 0
    )
    q = StreamingQuery(
        Identity(), src, sink, args.ckpt,
        max_batch_offsets=1, breakers=default_breakers(),
        pipeline_depth=3 if args.pipelined else 2,
        overlap_sink=args.pipelined,
        shape_buckets=4 if args.pipelined else 0,
    )
    if not args.serve:
        n = q.process_available()
        print(json.dumps({"batches": n}))
        return 0
    sup = QuerySupervisor(q, health_json=os.path.join(args.ckpt, "health.json"))
    sup.install_signal_handlers()
    status = sup.run(poll_interval=args.poll_interval)
    print(json.dumps({"batches": status["engine"]["batches_done"],
                      "drained": status["drained"]}))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--serve", action="store_true",
                    help="worker: supervised loop instead of one pass")
    ap.add_argument("--pipelined", action="store_true",
                    help="run the engine in pipelined mode (prefetching "
                    "source + shape buckets + overlapped sink delivery); "
                    "the matrix still compares against the serial "
                    "reference")
    ap.add_argument("--watch")
    ap.add_argument("--out")
    ap.add_argument("--ckpt")
    ap.add_argument("--slow-sink-s", type=float, default=0.0)
    ap.add_argument("--poll-interval", type=float, default=0.05)
    ap.add_argument("--workdir", default=None,
                    help="matrix scratch dir (default: a fresh tempdir)")
    args = ap.parse_args(argv)
    if args.worker:
        return worker_main(args)
    workdir = args.workdir
    if workdir is None:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="chaos_matrix_")
    verdict = run_matrix(workdir, pipelined=args.pipelined)
    print(json.dumps(verdict, indent=1))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
