#!/usr/bin/env python
"""Static drift check: pipelining knobs across CLI ⇔ engine ⇔ docs.

The pipelined serving surface is one feature spread over three layers —
``python -m sntc_tpu serve`` flags, ``StreamingQuery``/``DirStreamSource``
constructor kwargs, and the tuning documentation — and each knob must
exist in all of them:

=====================  ==========================================
``--pipeline-depth``   ``StreamingQuery(pipeline_depth=...)``
``--shape-buckets``    ``StreamingQuery(shape_buckets=...)``
``--prefetch-batches`` ``DirStreamSource(prefetch_batches=...)``
=====================  ==========================================

plus the engine-only ``overlap_sink`` kwarg, which the CLI derives from
``--pipeline-depth`` and the docs must therefore explain.  Every flag
must appear in ``docs/PERFORMANCE.md`` AND the README serve section.
Wired as a tier-1 test (``tests/test_streaming.py``) so the three
layers cannot drift silently — the ``check_fault_sites.py`` discipline
applied to the perf surface.

Exit 0 when consistent; exit 1 with a per-knob report otherwise.
"""

from __future__ import annotations

import inspect
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (CLI flag, owner import path, constructor kwarg)
FLAGS = (
    ("--pipeline-depth", "StreamingQuery", "pipeline_depth"),
    ("--shape-buckets", "StreamingQuery", "shape_buckets"),
    ("--prefetch-batches", "DirStreamSource", "prefetch_batches"),
)
ENGINE_ONLY_KWARGS = (("StreamingQuery", "overlap_sink"),)
DOCS = ("docs/PERFORMANCE.md", "README.md")


def _read(rel: str) -> str:
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _owner(name: str):
    sys.path.insert(0, REPO)
    from sntc_tpu.serve.streaming import DirStreamSource, StreamingQuery

    return {"StreamingQuery": StreamingQuery,
            "DirStreamSource": DirStreamSource}[name]


def check() -> list:
    """Returns a list of human-readable drift complaints (empty = ok)."""
    problems = []
    app_src = _read(os.path.join("sntc_tpu", "app.py"))
    doc_srcs = {rel: _read(rel) for rel in DOCS}
    for flag, owner_name, kwarg in FLAGS:
        if f'"{flag}"' not in app_src:
            problems.append(
                f"serve CLI flag {flag!r} missing from sntc_tpu/app.py"
            )
        params = inspect.signature(_owner(owner_name).__init__).parameters
        if kwarg not in params:
            problems.append(
                f"{owner_name}.__init__ lacks the {kwarg!r} kwarg that "
                f"{flag!r} maps to"
            )
        for rel, src in doc_srcs.items():
            if flag not in src:
                problems.append(f"{flag!r} undocumented in {rel}")
    for owner_name, kwarg in ENGINE_ONLY_KWARGS:
        params = inspect.signature(_owner(owner_name).__init__).parameters
        if kwarg not in params:
            problems.append(
                f"{owner_name}.__init__ lacks the {kwarg!r} kwarg"
            )
        for rel, src in doc_srcs.items():
            if kwarg not in src:
                problems.append(f"{kwarg!r} undocumented in {rel}")
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("pipelining-flag drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {len(FLAGS)} pipelining flags consistent across CLI, "
        "engine kwargs, and docs"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
