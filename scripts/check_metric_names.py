#!/usr/bin/env python
"""Static drift check: metric names across code ⇔ CATALOG ⇔ docs.

The telemetry substrate (``sntc_tpu.obs``) declares every metric the
codebase may emit in ``obs.metrics.CATALOG`` — name, type, labels,
help.  Three things must stay in lockstep or the plane silently rots:

1. **code → CATALOG**: every ``"sntc_*"`` metric-name literal used in
   the source must be declared (the registry enforces this at runtime
   too, but a dynamic-only check fires after the regression shipped);
2. **CATALOG → code**: every declared metric must be emitted somewhere
   — an unemitted catalog row is dead telemetry documentation;
3. **CATALOG ⇔ docs**: ``docs/OBSERVABILITY.md`` carries a
   marker-delimited metric-catalog table; every cataloged name must
   have a row and every row must name a cataloged metric, with the
   documented type matching.

Wired as a tier-1 test (``tests/test_obs.py``), the same discipline as
``check_tenant_flags.py`` / ``check_fault_sites.py``.

Exit 0 when consistent; exit 1 with a per-name report otherwise.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DOC = "docs/OBSERVABILITY.md"
TABLE_BEGIN = "<!-- metric-catalog:begin -->"
TABLE_END = "<!-- metric-catalog:end -->"
README_NEEDLE = "--metrics-out"

#: files/dirs scanned for metric-name literals (code emitters)
CODE_ROOTS = ("sntc_tpu", "bench.py", "scripts")

# metric names end in a unit/kind suffix by convention (the registry
# enforces CATALOG membership at runtime; this narrows the static scan
# past unrelated "sntc_*" literals like the package name itself)
_NAME_RE = re.compile(
    r'"(sntc_[a-z0-9_]+_(?:total|seconds|bytes|state|deficit|'
    r'divergence|flows|packets|depth|value|compliant|files|'
    r'signatures|connections|ratio|devices|batches))"'
)


def _read(rel: str) -> str:
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _code_names() -> set:
    """Every sntc_* string literal in the scanned sources, except the
    CATALOG declaration file itself and this checker."""
    names = set()
    skip = {
        os.path.join(REPO, "sntc_tpu", "obs", "metrics.py"),
        os.path.abspath(__file__),
    }
    for root in CODE_ROOTS:
        path = os.path.join(REPO, root)
        files = []
        if os.path.isfile(path):
            files = [path]
        else:
            for dirpath, _dirs, fnames in os.walk(path):
                if "__pycache__" in dirpath:
                    continue
                files.extend(
                    os.path.join(dirpath, f)
                    for f in fnames
                    if f.endswith(".py")
                )
        for f in files:
            if os.path.abspath(f) in skip:
                continue
            with open(f) as fh:
                names.update(_NAME_RE.findall(fh.read()))
    return names


def _doc_rows() -> dict:
    """name -> documented type, from the marker-delimited table."""
    text = _read(DOC)
    if TABLE_BEGIN not in text or TABLE_END not in text:
        return {}
    table = text.split(TABLE_BEGIN, 1)[1].split(TABLE_END, 1)[0]
    rows = {}
    for line in table.splitlines():
        m = re.match(r"\s*\|\s*`(sntc_[a-z0-9_]+)`\s*\|\s*(\w+)", line)
        if m:
            rows[m.group(1)] = m.group(2)
    return rows


def check() -> list:
    """Returns human-readable drift complaints (empty = consistent)."""
    problems = []
    sys.path.insert(0, REPO)
    from sntc_tpu.obs.metrics import CATALOG

    code = _code_names()
    doc = _doc_rows()
    if not doc:
        problems.append(
            f"{DOC} is missing the marker-delimited metric-catalog "
            f"table ({TABLE_BEGIN} ... {TABLE_END})"
        )
    for name in sorted(code - set(CATALOG)):
        problems.append(
            f"code emits {name!r} but obs.metrics.CATALOG does not "
            "declare it"
        )
    for name in sorted(set(CATALOG) - code):
        problems.append(
            f"CATALOG declares {name!r} but no code emits it — dead "
            "telemetry declaration"
        )
    for name, spec in sorted(CATALOG.items()):
        if doc and name not in doc:
            problems.append(
                f"CATALOG metric {name!r} missing from the {DOC} "
                "catalog table"
            )
        elif doc and doc[name] != spec["type"]:
            problems.append(
                f"{name!r}: docs say type {doc[name]!r}, CATALOG says "
                f"{spec['type']!r}"
            )
    for name in sorted(set(doc) - set(CATALOG)):
        problems.append(
            f"{DOC} documents {name!r} but CATALOG does not declare it"
        )
    if README_NEEDLE not in _read("README.md"):
        problems.append(
            "README.md has no --metrics-out observability quickstart"
        )
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("metric-name drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    from sntc_tpu.obs.metrics import CATALOG

    print(
        f"ok: {len(CATALOG)} metrics consistent across code, "
        "obs.metrics.CATALOG, and docs/OBSERVABILITY.md"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
