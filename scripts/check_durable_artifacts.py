#!/usr/bin/env python
"""Static drift check: durable write sites ⇔ storage registry ⇔ docs.

The durable-storage survival plane (``sntc_tpu/resilience/storage.py``,
r17) only bounds what it knows about.  Three things must stay in
lockstep or an append-forever file ships silently:

1. **write sites → registry**: every raw append (``open(..., "a")``)
   and every atomic publish (``os.replace(...)``) in ``sntc_tpu/``
   either lives inside the storage plane itself, or carries a
   ``# storage: <artifact>`` annotation naming a registered
   :data:`~sntc_tpu.resilience.storage.ARTIFACTS` entry — XOR an
   explicit ``# storage: unbounded(<reason>)`` declaring it
   deliberately outside the lifecycle (sink output, caller-owned log
   paths).  An unannotated write site is exactly the silent
   grow-forever (or torn-file) surface this plane exists to end.
2. **registry → docs**: every registered artifact has a row in the
   marker-delimited durable-artifacts table of ``docs/RESILIENCE.md``
   (name + retention + failure policy), and every row names a
   registered artifact with the policy the code declares.
3. **fault grammar**: the IO kinds (``enospc`` / ``io_error`` /
   ``torn_write``) are in ``ALL_KINDS`` and documented in the
   fault-kinds table (``check_fault_sites.py`` owns the full kinds
   table ⇔ ALL_KINDS check; this pins the IO subset exists at all),
   and every registered artifact's fault site is a declared SITES
   entry.

Wired as a tier-1 test (``tests/test_storage.py``), the same
discipline as ``check_fault_sites.py`` / ``check_metric_names.py``.

Exit 0 when consistent; exit 1 with a per-site report otherwise.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DOC = "docs/RESILIENCE.md"
TABLE_BEGIN = "<!-- durable-artifacts:begin -->"
TABLE_END = "<!-- durable-artifacts:end -->"

# a raw durable-write call: an append-mode open or an atomic rename
_WRITE_RE = re.compile(
    r"""open\([^)\n]*["']a["']|os\.replace\("""
)
_ANNOTATION_RE = re.compile(
    r"#\s*storage:\s*([A-Za-z0-9_-]+(?:\([^)]*\))?)"
)
_UNBOUNDED_RE = re.compile(r"^unbounded\(.+\)$")
# the blessed module: every write inside it IS the storage plane
_STORAGE_MODULE = os.path.join("resilience", "storage.py")

_ROW_RE = re.compile(
    r"^\|\s*`([A-Za-z0-9_]+)`\s*\|[^|]*\|[^|]*\|\s*`?"
    r"(fail|degrade|shed)`?\s*\|",
    re.MULTILINE,
)


def _read(rel: str) -> str:
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def registry():
    sys.path.insert(0, REPO)
    from sntc_tpu.resilience.storage import ARTIFACTS

    return ARTIFACTS


def write_sites() -> list:
    """Every raw durable-write line in sntc_tpu/ with its annotation
    (or None): [(rel_path, lineno, annotation)]."""
    out = []
    root = os.path.join(REPO, "sntc_tpu")
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, REPO)
            if rel.endswith(_STORAGE_MODULE):
                continue
            with open(path) as f:
                for i, line in enumerate(f, 1):
                    if not _WRITE_RE.search(line):
                        continue
                    m = _ANNOTATION_RE.search(line)
                    out.append((rel, i, m.group(1) if m else None))
    return out


def documented_artifacts() -> dict:
    """{artifact: documented_policy} from the docs table."""
    text = _read(DOC)
    if TABLE_BEGIN not in text or TABLE_END not in text:
        return {}
    table = text.split(TABLE_BEGIN, 1)[1].split(TABLE_END, 1)[0]
    return dict(_ROW_RE.findall(table))


def check() -> list:
    problems = []
    artifacts = registry()

    # 1. write sites annotated, annotations valid
    for rel, lineno, ann in write_sites():
        where = f"{rel}:{lineno}"
        if ann is None:
            problems.append(
                f"{where}: durable write (append/os.replace) with no "
                "'# storage: <artifact>' annotation — register it with "
                "the storage plane or declare it "
                "'# storage: unbounded(<reason>)'"
            )
        elif ann == "registered-artifact":
            pass  # the writer helper's own parametric site
        elif _UNBOUNDED_RE.match(ann):
            pass
        elif ann not in artifacts:
            problems.append(
                f"{where}: annotation '# storage: {ann}' names no "
                "registered ARTIFACTS entry"
            )

    # 2. registry ⇔ docs table
    documented = documented_artifacts()
    if not documented:
        problems.append(
            f"{DOC} is missing the marker-delimited durable-artifacts "
            f"table ({TABLE_BEGIN} ... {TABLE_END})"
        )
    else:
        for name, spec in sorted(artifacts.items()):
            if name not in documented:
                problems.append(
                    f"artifact {name!r} is registered in "
                    "resilience.storage.ARTIFACTS but missing from the "
                    f"{DOC} durable-artifacts table"
                )
            elif documented[name] != spec.failure_policy:
                problems.append(
                    f"artifact {name!r}: docs table says policy "
                    f"{documented[name]!r} but the registry declares "
                    f"{spec.failure_policy!r}"
                )
        for name in sorted(set(documented) - set(artifacts)):
            problems.append(
                f"{DOC} durable-artifacts table documents {name!r} but "
                "resilience.storage.ARTIFACTS does not register it"
            )

    # 3. fault grammar: IO kinds declared + documented, artifact sites
    # declared
    sys.path.insert(0, REPO)
    from sntc_tpu.resilience import ALL_KINDS, IO_KINDS, SITES

    for kind in IO_KINDS:
        if kind not in ALL_KINDS:
            problems.append(
                f"IO kind {kind!r} missing from ALL_KINDS"
            )
    kinds_doc = _read(DOC)
    for kind in IO_KINDS:
        if f"`{kind}`" not in kinds_doc:
            problems.append(
                f"IO kind {kind!r} undocumented in {DOC}"
            )
    for name, spec in sorted(artifacts.items()):
        if spec.site not in SITES:
            problems.append(
                f"artifact {name!r} declares fault site {spec.site!r} "
                "which is not in sntc_tpu.resilience.SITES"
            )
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("durable-artifact drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    n_sites = len(write_sites())
    print(
        f"ok: {n_sites} durable write sites annotated, "
        f"{len(registry())} artifacts consistent across registry and "
        "docs"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
