#!/usr/bin/env python
"""Static drift check: fleet knobs across CLI ⇔ FleetCoordinator ⇔ docs.

The elastic serve fleet (r19) is one feature spread over three layers
— ``python -m sntc_tpu fleet-serve`` flags, the
:class:`sntc_tpu.serve.fleet.FleetCoordinator` keyword arguments they
fill, and the marker-delimited fleet-flags table in
``docs/RESILIENCE.md`` — and each knob must exist in all of them:

==================== ==============================
``--workers``        (CLI-only: spawn count)
``--worker-ids``     (CLI-only: explicit ids)
``--lease-ttl``      ``lease_ttl_s``
``--boot-grace``     ``boot_grace_s``
``--dead-grace``     ``dead_grace_s``
``--vnodes``         ``vnodes``
``--slack``          ``slack``
``--drain-timeout``  (CLI-only: SIGTERM fan-out window)
``--fleet-worker-id``(CLI-only: worker-child re-invocation)
==================== ==============================

Every flag (and its coordinator kwarg, where one exists) must appear
in the fleet-flags table, every ``FleetCoordinator`` tunable must be
reachable from the CLI, and the README must carry a fleet-serve
quickstart.  Wired as a tier-1 test (``tests/test_fleet.py``) so the
three layers cannot drift silently — the ``check_tenant_flags.py``
discipline applied to the fleet surface.

Exit 0 when consistent; exit 1 with a per-knob report otherwise.
"""

from __future__ import annotations

import inspect
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (fleet-serve CLI flag, FleetCoordinator kwarg it fills, or None for
# flags consumed by the CLI process-supervision layer itself)
FLAGS = (
    ("--workers", None),
    ("--worker-ids", None),
    ("--lease-ttl", "lease_ttl_s"),
    ("--boot-grace", "boot_grace_s"),
    ("--dead-grace", "dead_grace_s"),
    ("--vnodes", "vnodes"),
    ("--slack", "slack"),
    ("--drain-timeout", None),
    ("--fleet-worker-id", None),
)
# coordinator ctor params that are NOT CLI-surfaced on purpose:
# positional wiring plus test-injection seams; standby_root is surfaced
# by --standby-root on the SHARED daemon/fleet parser (not the
# fleet-serve subparser block this checker scans) and its CLI ⇔ plane ⇔
# docs drift is owned by check_repl_flags.py
_CTOR_INTERNAL = {"self", "root", "worker_ids", "specs_by_id", "wall",
                  "scale_out_hook", "standby_root"}
DOC = "docs/RESILIENCE.md"
TABLE_BEGIN = "<!-- fleet-flags:begin -->"
TABLE_END = "<!-- fleet-flags:end -->"
README_NEEDLE = "fleet-serve"


def _read(rel: str) -> str:
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _doc_table() -> str:
    text = _read(DOC)
    if TABLE_BEGIN not in text or TABLE_END not in text:
        return ""
    return text.split(TABLE_BEGIN, 1)[1].split(TABLE_END, 1)[0]


def check() -> list:
    """Returns a list of human-readable drift complaints (empty = ok)."""
    problems = []
    app_src = _read(os.path.join("sntc_tpu", "app.py"))
    # flags must be declared inside the fleet-serve subparser block
    fleet_src = app_src.split('sub.add_parser(\n        "fleet-serve"', 1)
    fleet_src = fleet_src[1] if len(fleet_src) == 2 else ""
    sys.path.insert(0, REPO)
    from sntc_tpu.serve.fleet import FleetCoordinator

    sig = inspect.signature(FleetCoordinator.__init__)
    ctor_kwargs = set(sig.parameters) - _CTOR_INTERNAL
    table = _doc_table()
    if not table:
        problems.append(
            f"{DOC} is missing the marker-delimited fleet-flags table "
            f"({TABLE_BEGIN} ... {TABLE_END})"
        )
    for flag, kwarg in FLAGS:
        if f'"{flag}"' not in fleet_src:
            problems.append(
                f"fleet-serve CLI flag {flag!r} missing from the "
                "fleet-serve parser in sntc_tpu/app.py"
            )
        if kwarg is not None and kwarg not in ctor_kwargs:
            problems.append(
                f"FleetCoordinator has no {kwarg!r} kwarg for {flag!r} "
                "to fill"
            )
        if table and flag not in table:
            problems.append(
                f"{flag!r} missing from the {DOC} fleet-flags table"
            )
        if table and kwarg is not None and f"`{kwarg}`" not in table:
            problems.append(
                f"FleetCoordinator kwarg {kwarg!r} missing from the "
                f"{DOC} fleet-flags table"
            )
    # every coordinator tunable must be reachable from the CLI
    mapped = {k for _, k in FLAGS if k is not None}
    for kwarg in sorted(ctor_kwargs - mapped):
        problems.append(
            f"FleetCoordinator kwarg {kwarg!r} has no fleet-serve CLI "
            "flag (add one, or list it in _CTOR_INTERNAL with a reason)"
        )
    # the reverse direction: every table row must be a known flag
    for row_flag in re.findall(r"`(--[a-z-]+)`", table):
        if row_flag not in {f for f, _ in FLAGS}:
            problems.append(
                f"{DOC} fleet-flags table documents {row_flag!r} but "
                "the checker's FLAGS mapping does not declare it"
            )
    if README_NEEDLE not in _read("README.md"):
        problems.append("README.md has no fleet-serve quickstart")
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("fleet-flag drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {len(FLAGS)} fleet flags consistent across the "
        "fleet-serve CLI, FleetCoordinator kwargs, and docs"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
