#!/usr/bin/env python
"""Static drift check: mesh axis names in code ⇔ MESH_AXES ⇔ docs.

The r22 mesh substrate (``sntc_tpu/parallel/mesh.py``) declares the
axis vocabulary once, in ``MESH_AXES`` — every ``PartitionSpec``,
``lax.psum`` and ``axis_name=`` literal anywhere in ``sntc_tpu/`` must
resolve to one of those names, every registry key must be backed by a
``*_AXIS = "<name>"`` constant in the substrate module, and the
marker-delimited axis table in ``docs/PERFORMANCE.md`` must list
exactly the registry, both directions.  The check also enforces the
substrate boundary itself: no module outside ``parallel/mesh.py`` /
``parallel/compat.py`` may reach for ``shard_map`` or ``pmap``
directly — sharded dispatch goes through ``map_at``/``map_reduce_at``
so placement, evidence metrics, and elastic resize stay in one place.

Wired as a tier-1 test (``tests/test_mesh.py``) so code, registry, and
docs cannot diverge silently.  Exit 0 when consistent; exit 1 with a
per-direction report otherwise.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SUBSTRATE = os.path.join("parallel", "mesh.py")
_COMPAT = os.path.join("parallel", "compat.py")

# axis-name string literals at sharding call sites
_AXIS_LITERAL_RES = (
    # P("data", ...) / PartitionSpec("data", ...) — any positional
    # string literal names an axis
    re.compile(r"(?:\bP|PartitionSpec)\(([^)]*)\)"),
)
_PSUM_RE = re.compile(r"""lax\.psum\([^,)]+,\s*["']([A-Za-z0-9_]+)["']""")
_KWARG_RE = re.compile(r"""axis_name\s*[:=]\s*["']([A-Za-z0-9_]+)["']""")
_MESH_TUPLE_RE = re.compile(
    r"""Mesh\([^)]*\(\s*((?:["'][A-Za-z0-9_]+["']\s*,?\s*)+)\)"""
)
_CONST_RE = re.compile(r"""^[A-Z0-9_]*_AXIS\s*=\s*["']([A-Za-z0-9_]+)["']""",
                       re.MULTILINE)
_STR_RE = re.compile(r"""["']([A-Za-z0-9_]+)["']""")

# docs table between these markers: | `axis` | carries | collectives |
_AXES_BEGIN = "<!-- mesh-axes:begin -->"
_AXES_END = "<!-- mesh-axes:end -->"
_DOC_ROW_RE = re.compile(r"^\|\s*`([A-Za-z0-9_]+)`\s*\|", re.MULTILINE)

_FORBIDDEN_RE = re.compile(r"\b(?:shard_map|pmap)\b")


def _py_files(root=None):
    root = root or os.path.join(REPO, "sntc_tpu")
    for dirpath, _, files in os.walk(root):
        for name in files:
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def code_axis_literals() -> set:
    """Axis names used as string literals at sharding call sites
    anywhere in sntc_tpu/ (including the substrate module's own
    constants)."""
    found = set()
    for path in _py_files():
        with open(path) as f:
            text = f.read()
        for call_re in _AXIS_LITERAL_RES:
            for args in call_re.findall(text):
                found.update(_STR_RE.findall(args))
        found.update(_PSUM_RE.findall(text))
        found.update(_KWARG_RE.findall(text))
        for body in _MESH_TUPLE_RE.findall(text):
            found.update(_STR_RE.findall(body))
    return found


def substrate_constants() -> set:
    """The ``*_AXIS = "<name>"`` constants defined by the substrate."""
    with open(os.path.join(REPO, "sntc_tpu", _SUBSTRATE)) as f:
        return set(_CONST_RE.findall(f.read()))


def declared_axes() -> set:
    sys.path.insert(0, REPO)
    from sntc_tpu.parallel.mesh import MESH_AXES

    return set(MESH_AXES)


def documented_axes(doc_path=None) -> set:
    doc_path = doc_path or os.path.join(REPO, "docs", "PERFORMANCE.md")
    with open(doc_path) as f:
        text = f.read()
    if _AXES_BEGIN not in text or _AXES_END not in text:
        return set()  # reported as a drift problem by check()
    table = text.split(_AXES_BEGIN, 1)[1].split(_AXES_END, 1)[0]
    return {a for a in _DOC_ROW_RE.findall(table) if a != "axis"}


def forbidden_call_sites() -> list:
    """Modules outside the substrate that name shard_map/pmap."""
    offenders = []
    for path in _py_files():
        rel = os.path.relpath(path, os.path.join(REPO, "sntc_tpu"))
        if rel in (_SUBSTRATE, _COMPAT):
            continue
        with open(path) as f:
            text = f.read()
        if _FORBIDDEN_RE.search(text):
            offenders.append(rel)
    return sorted(offenders)


def check() -> list:
    """Returns a list of human-readable drift complaints (empty = ok)."""
    in_code = code_axis_literals()
    constants = substrate_constants()
    declared = declared_axes()
    documented = documented_axes()
    problems = []
    if not documented:
        problems.append(
            "docs/PERFORMANCE.md is missing the marker-delimited mesh-"
            f"axes table ({_AXES_BEGIN} ... {_AXES_END})"
        )
    for axis in sorted(in_code - declared):
        problems.append(
            f"axis literal {axis!r} is used at a sharding call site but "
            "is not a MESH_AXES key (sntc_tpu/parallel/mesh.py)"
        )
    for axis in sorted(declared - constants):
        problems.append(
            f"MESH_AXES declares {axis!r} but parallel/mesh.py defines "
            f"no *_AXIS = \"{axis}\" constant for call sites to import"
        )
    for axis in sorted(declared - documented) if documented else ():
        problems.append(
            f"MESH_AXES declares {axis!r} but the docs/PERFORMANCE.md "
            "axis table does not document it"
        )
    for axis in sorted(documented - declared):
        problems.append(
            f"docs/PERFORMANCE.md documents axis {axis!r} but MESH_AXES "
            "does not declare it"
        )
    for rel in forbidden_call_sites():
        problems.append(
            f"sntc_tpu/{rel} names shard_map/pmap directly — sharded "
            "dispatch must go through parallel/mesh.py (map_at / "
            "map_reduce_at / sharded_jit)"
        )
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("mesh-axis drift detected:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {len(declared_axes())} mesh axes consistent across code "
        "literals, MESH_AXES, and docs; substrate boundary clean"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
